"""Estimator-style wrapper: fit / transform / components, sklearn-shaped.

The reference validates its result by eyeballing a scatter of ``data @ W``
against ``sklearn.decomposition.PCA(2)`` (notebook cells 17-22). This class
packages the same workflow — ``W = fit(data)``, ``transform(x) = x @ W`` —
as a real API, with the worker pool and online loop behind it.

``fit`` dispatches to the measured-fastest trainer for the workload
(:func:`choose_trainer` — the whole-fit scan/segmented/sketch trainers the
benchmark numbers come from), so the public API reaches the same
throughput path as ``bench.py``; ``trainer=`` overrides.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distributed_eigenspaces_tpu.config import PCAConfig
from distributed_eigenspaces_tpu.algo.online import (
    OnlineState,
    online_distributed_pca,
)
from distributed_eigenspaces_tpu.data.stream import block_stream
from distributed_eigenspaces_tpu.parallel.worker_pool import WorkerPool

TRAINERS = ("auto", "step", "scan", "segmented", "sketch", "fleet")


def _scan_mesh(cfg: PCAConfig):
    """Worker mesh for the dense whole-fit trainers (None = single-device);
    mirrors the per-step backend selection: explicit shard_map/tpu, or
    auto with >1 device."""
    if cfg.backend in ("shard_map", "tpu") or (
        cfg.backend == "auto" and len(jax.devices()) > 1
    ):
        from distributed_eigenspaces_tpu.parallel.mesh import (
            largest_divisor_leq,
            make_mesh,
        )

        workers = largest_divisor_leq(cfg.num_workers, len(jax.devices()))
        if workers > 1:
            return make_mesh(num_workers=workers)
    return None

# Measured crossover (BASELINE.md "Negative result" + the round-5
# boundary sweep, scripts/exp_crossover.py): the Nystrom-sketch steady
# state — zero per-step spectral solves — wins 4x at d=12288/k=50
# (d*k = 614k), 13.5x at 123k, 6.6x at 74k, and still 5.3x at 49k
# BELOW this boundary, but LOSES 2.5x at d=1024/k=8 (d*k = 8k; the
# avoided eigh(64^2) was already cheap). The constant is therefore NOT
# the speed crossover (that sits somewhere in 8k-49k): it is the
# accuracy-conservative routing point — at 49k the exact trainer
# matches the batch-PCA oracle (0.34 deg = 0.34 oracle) while the
# sketch adds ~0.25 deg of drift, so auto keeps exactness below the
# boundary and takes the measured >=6.6x above it (where the drift is
# bounded and warned about; trainer='step'/'scan' overrides remain).
SKETCH_DK_CROSSOVER = 65536

# Dense whole-fit staging threshold: the scan trainer wants the whole
# (T, m, n, d) schedule device-resident, which stops being reasonable long
# before HBM actually fills (one v5e chip has 16 GB, shared with the d x d
# state and program temps — a 4.3 GB stage measurably RESOURCE_EXHAUSTs
# alongside a second fit's buffers). Above this, the segmented trainer
# runs the same programs over host-resident data with O(segment) device
# staging, at ~1/segment of the per-step dispatch cost.
SCAN_STAGE_BYTES_MAX = 1 << 31  # 2 GiB


def resolves_feature_sharded(cfg: PCAConfig, *, whole_fit: bool = True) -> bool:
    """ONE definition of "this workload runs the feature-sharded backend":
    explicit; ``auto`` at d >= 4096, where a dense d x d state must not
    exist; or — for WHOLE fits only — ``auto`` above the measured
    ``d*k`` crossover, where the sketch trainer's solve-free steady
    state wins regardless of d. Round-4 measurement: at d=768/k=256
    (config 5's shapes, d*k=197k) the sketch runs 17.9M samples/s vs
    the dense scan's 0.50M at BETTER accuracy (0.151 vs 0.307 deg),
    because the dense warm step is buried under k=256-sized
    eigh/Cholesky latency. ``whole_fit=False`` (the per-step
    continuation paths — hooks, fit_stream, partial_fit) keeps the d*k
    clause OUT: the per-step loop never runs the sketch, so those
    configs would trade the exact dense state for a rank-truncated one
    with no measured benefit. Shared by the trainer chooser, the
    whole-fit executor and the continuation path so the dispatch sites
    cannot drift."""
    if cfg.backend == "feature_sharded":
        return True
    if cfg.backend != "auto":
        return False
    if cfg.dim >= 4096:
        return True
    return whole_fit and cfg.dim * cfg.k >= SKETCH_DK_CROSSOVER


def choose_trainer(
    cfg: PCAConfig,
    *,
    per_step_hooks: bool = False,
    checkpointing: bool = False,
) -> str:
    """Pick the measured-fastest trainer for a whole-dataset ``fit``.

    Encodes BASELINE.md's measurements as code (round-2 verdict item 2):

    - per-step hooks (``on_step`` / ``worker_masks``) need host control
      between rounds -> the per-step trainer;
    - the feature-sharded backend (:func:`resolves_feature_sharded`) gets
      the sketch trainer above the measured ``d*k`` crossover, its exact
      scan fit below;
    - dense workloads get the whole-fit scan — the benchmark's headline
      path — or its segmented twin when checkpointing is requested OR
      the staged ``(T, m, n, d)`` schedule exceeds
      ``SCAN_STAGE_BYTES_MAX`` (same semantics and compiled programs;
      the segmented fit keeps the data host-resident and stages
      O(segment) on device). The feature-sharded trainers handle both
      conditions themselves: their windowed entry (``fit_windows``)
      checkpoints per window and stages O(window) per device, so the
      trainer name never changes — ``fit`` picks windowed execution
      when checkpointing or when the staged stack would bust the
      per-device budget.
    """
    if per_step_hooks:
        return "step"
    if resolves_feature_sharded(cfg):
        if cfg.dim * cfg.k >= SKETCH_DK_CROSSOVER:
            return "sketch"
        return "scan"
    itemsize = cfg.resolved_stage_dtype().itemsize
    staged = (
        cfg.num_steps * cfg.num_workers * cfg.rows_per_worker * cfg.dim
        * itemsize
    )
    if checkpointing or staged > SCAN_STAGE_BYTES_MAX:
        return "segmented"
    return "scan"


def _budget_steps(cfg: PCAConfig, n_devices: int = 1) -> int:
    """Max schedule steps the per-device staging budget allows — ONE
    definition of ``SCAN_STAGE_BYTES_MAX * devices // step_bytes`` for
    the feature-sharded whole fit, the segmented fit, and the sketch
    continuation (a copy that drifts would stage windows another path
    would have rejected, OOMing at exactly the large-d sizes the budget
    exists for)."""
    step_bytes = (
        cfg.num_workers * cfg.rows_per_worker * cfg.dim
        * cfg.resolved_stage_dtype().itemsize
    )
    return max(
        1, SCAN_STAGE_BYTES_MAX * max(n_devices, 1) // max(step_bytes, 1)
    )


def _validated_masks(worker_masks, num_workers: int) -> np.ndarray:
    """Shape-check a (T, m) worker-mask sequence — shared by every
    masked whole-fit route."""
    worker_masks = np.asarray(worker_masks, np.float32)
    if worker_masks.ndim != 2 or worker_masks.shape[1] != num_workers:
        raise ValueError(
            f"worker_masks shape {worker_masks.shape} != "
            f"(T, num_workers={num_workers})"
        )
    return worker_masks


def _masks_for(worker_masks: np.ndarray, t: int) -> np.ndarray:
    """First ``t`` mask rows; raises when the supply is short — a
    silently dropped step's mask is the §5.3 bug class this guards."""
    if len(worker_masks) < t:
        raise ValueError(
            f"worker_masks covers {len(worker_masks)} steps; the "
            f"schedule runs {t} — every step needs its mask row"
        )
    return worker_masks[:t]


def _lockstep_mask_windows(windows, take_rows):
    """Mask windows SHAPED BY the data windows, not pre-windowed: the
    schedule's actual step count belongs to the data (a truncating
    dataset must behave exactly like the staged mode). ``fit_windows``'s
    strict zip pulls a data window first, so its recorded size is always
    available when the mask side is pulled — under prefetch the data
    side only runs further AHEAD. ``take_rows(start, size)`` returns the
    ``(size, m)`` mask rows covering steps ``[start, start+size)`` (and
    raises on a short mask supply). Returns the tapped window iterator
    plus the lockstep mask iterator — ONE copy of this machinery for the
    whole-fit and continuation paths."""
    sizes: list[int] = []

    def tapped():
        for w in windows:
            sizes.append(int(w.shape[0]))
            yield w

    def masks():
        idx = 0
        taken = 0
        while idx < len(sizes):  # grows while iterating
            s = sizes[idx]
            idx += 1
            yield take_rows(taken, s)
            taken += s

    return tapped(), masks()


def _routes_feature_whole(cfg: PCAConfig, trainer: str) -> bool:
    """Whether this (cfg, resolved trainer) pair executes the
    feature-sharded whole-fit programs — THE routing condition
    ``_fit_whole`` dispatches on, shared with ``fit``'s worker_masks
    validation so "can these masks ride a masked whole fit" can never
    disagree with where the fit actually runs (round-4 review: an
    explicit ``trainer='sketch'`` routes feature-sharded regardless of
    backend, and ``trainer='segmented'`` never does)."""
    return trainer == "sketch" or (
        trainer == "scan" and resolves_feature_sharded(cfg)
    )


class OnlineDistributedPCA:
    """Online distributed PCA estimator.

    Example (the notebook cell 16-20 workflow, one call)::

        pca = OnlineDistributedPCA(PCAConfig(dim=1024, k=2, num_workers=10,
                                             rows_per_worker=8, num_steps=10))
        pca.fit(data)                  # data: (N, 1024)
        z = pca.transform(data)        # (N, 2)
        W = pca.components_            # (1024, 2), descending, canonical signs
    """

    def __init__(
        self,
        cfg: PCAConfig,
        *,
        pool: WorkerPool | None = None,
        trainer: str = "auto",
        checkpoint_dir: str | None = None,
        segment: int = 50,
    ):
        if trainer not in TRAINERS:
            raise ValueError(
                f"unknown trainer {trainer!r}; one of {TRAINERS}"
            )
        self.cfg = cfg
        self.pool = pool
        self.trainer = trainer
        self.checkpoint_dir = checkpoint_dir
        self.segment = segment
        self.state = None
        #: the trainer the last ``fit`` actually ran (``choose_trainer``
        #: resolution recorded — so callers can tell exact results from
        #: the sketch trainer's bounded-drift approximation)
        self.trainer_used_: str | None = None
        self._w: jax.Array | None = None
        # compiled sketch trainer, cached across partial_fit/fit_stream
        # continuations (rebuilding per call would recompile per call)
        self._sketch_fit = None
        # transform kernels backed by the persistent compile cache
        # (built lazily when cfg.compile_cache_dir is set)
        self._transform_engine = None

    def _compile_cache(self):
        """The persistent AOT store for ``cfg.compile_cache_dir``, or
        None — resolved per call (the registry in
        ``utils.compile_cache`` is a per-directory singleton, so this
        is cheap and survives unpickling)."""
        from distributed_eigenspaces_tpu.utils.compile_cache import (
            compile_cache_for,
        )

        return compile_cache_for(self.cfg)

    # -- fitting ------------------------------------------------------------

    def fit(
        self, data, *, on_step=None, worker_masks=None, tracer=None
    ) -> "OnlineDistributedPCA":
        """Fit on a (N, dim) array, streaming it as ``num_steps`` blocks of
        ``num_workers x rows_per_worker`` rows (advancing cursor — B6 fix).

        ``tracer`` (a ``utils.telemetry.Tracer``) wraps the whole fit in
        a root span on a fresh ``fit`` trace — the run's arc on the
        exported timeline (CLI ``--trace-out``); ``None`` traces
        nothing.

        ``fit`` starts fresh (sklearn semantics — prior state is discarded);
        use :meth:`fit_stream`/:meth:`partial_fit` to continue a run.

        The trainer is picked by :func:`choose_trainer` unless overridden
        at construction: whole-dataset fits run the whole-fit trainers the
        benchmark measures (scan / segmented / sketch); ``on_step`` hooks
        or explicit ``trainer="step"`` run the per-step loop.
        ``worker_masks`` as a ``(T, m)`` SEQUENCE (array/list/tuple)
        runs the MASKED whole-fit trainers on EVERY whole-fit route —
        dense scan, segmented, feature-sharded scan, sketch (§5.3
        without giving up whole-fit throughput; round 5 closed the
        dense gap — previously a loud error). The mask count must
        cover the step schedule (short masks raise); a mask
        generator/iterator keeps the per-step loop, whose contract is
        one ``next()`` per round.
        """
        from distributed_eigenspaces_tpu.utils.telemetry import NULL_TRACER

        tr = tracer if tracer is not None else NULL_TRACER
        with tr.span(
            "estimator_fit", trace_id=tr.new_trace("fit"),
            category="fit", device=True,
            attrs={"dim": self.cfg.dim, "k": self.cfg.k,
                   "steps": self.cfg.num_steps},
        ) as sp:
            out = self._fit_impl(
                data, on_step=on_step, worker_masks=worker_masks
            )
            sp.set(trainer=self.trainer_used_)
            return out

    def _fit_impl(self, data, *, on_step, worker_masks):
        self.state = None
        self._w = None
        cfg = self.cfg
        trainer = self.trainer
        if cfg.pipeline_merge and self.checkpoint_dir is not None:
            # the pipelined scan's pending-factor carry is not
            # checkpointable state (make_segmented_fit rejects it for the
            # same reason) — fail HERE with the remedy, not three layers
            # down mid-dispatch
            raise ValueError(
                "pipeline_merge fits cannot checkpoint: the pipelined "
                "carry (pending worker factors) is not part of any saved "
                "state, so kill/resume could not be bit-for-bit. Drop "
                "checkpoint_dir, or use merge_interval alone (resume-"
                "safe: the merge phase derives from the step counter)."
            )
        # mask-only fits whose trainer routes to the feature-sharded
        # whole-fit programs run those programs MASKED (the per-step
        # loop's host control is only needed by on_step); a generator of
        # masks keeps the per-step contract (one next() per round —
        # length unknowable up front)
        masks_seq = (
            worker_masks is not None
            and on_step is None
            and isinstance(
                worker_masks, (np.ndarray, jax.Array, list, tuple)
            )
        )
        if trainer == "auto":
            trainer = choose_trainer(
                cfg,
                per_step_hooks=(on_step is not None),
                checkpointing=self.checkpoint_dir is not None,
            )
            if worker_masks is not None and not masks_seq:
                # mask generators can't ride a compiled whole fit (one
                # next() per round needs host control) — fall back to
                # the per-step loop; every whole-fit trainer has masked
                # programs for SEQUENCE masks since round 5
                trainer = choose_trainer(
                    cfg,
                    per_step_hooks=True,
                    checkpointing=self.checkpoint_dir is not None,
                )
        elif trainer != "step" and on_step is not None:
            raise ValueError(
                f"trainer={trainer!r} runs the whole fit as compiled "
                "programs — per-step on_step hooks need trainer='step' "
                "(or 'auto', which picks it for you)"
            )
        elif (
            trainer != "step"
            and worker_masks is not None
            and not masks_seq
        ):
            # a mask generator on an explicit whole-fit override: the
            # whole-fit programs need the full (T, m) schedule up front
            raise ValueError(
                f"trainer={trainer!r} takes worker_masks as a (T, m) "
                "sequence (array/list/tuple); use trainer='step' for a "
                "per-step mask generator"
            )
        masks_whole = trainer != "step" and worker_masks is not None
        if self.checkpoint_dir is not None and (
            trainer in ("step", "fleet")
            or (trainer == "scan" and not resolves_feature_sharded(cfg))
        ):
            # loud beats silent: a long fit that the user believes is
            # checkpointed but isn't would surface only after a crash.
            # Two ways here: an explicit trainer override, or per-step
            # hooks forcing 'auto' onto the per-step trainer (hooks need
            # host control between rounds, which the windowed whole-fit
            # programs don't hand back per step).
            raise ValueError(
                f"checkpoint_dir is honored by the whole-fit trainers "
                f"(segmented / feature-sharded scan / sketch); this fit "
                f"resolved to trainer={trainer!r}"
                + (
                    " because on_step/worker_masks hooks require the "
                    "per-step trainer. Drop the hooks, or checkpoint "
                    "from your own on_step hook via "
                    "utils.checkpoint.Checkpointer"
                    if trainer == "step" and self.trainer == "auto"
                    else ". Drop checkpoint_dir, drop the trainer "
                    "override (trainer='auto' picks a checkpointable "
                    "one), or checkpoint per-step state yourself via "
                    "utils.checkpoint in an on_step hook with "
                    "trainer='step'"
                )
            )
        self.trainer_used_ = trainer
        if trainer != "step":
            return self._fit_whole(
                data, trainer,
                worker_masks=worker_masks if masks_whole else None,
            )
        stream = block_stream(
            data,
            num_workers=cfg.num_workers,
            rows_per_worker=cfg.rows_per_worker,
            num_steps=cfg.num_steps,
            remainder=cfg.remainder,
            dtype=cfg.dtype,
        )
        return self.fit_stream(stream, on_step=on_step, worker_masks=worker_masks)

    def _fit_whole(
        self, data, trainer: str, worker_masks=None
    ) -> "OnlineDistributedPCA":
        """Whole-fit trainers: stage the T-step schedule and run it as one
        (or T/segment) compiled programs — the bench.py throughput path,
        now reachable from the public API (round-2 verdict item 2).
        ``worker_masks`` (a validated (T, m) sequence) reaches EVERY
        route since round 5: the dense scan and segmented fits run
        their masked programs (algo/scan.py), the feature-sharded
        routes theirs."""
        cfg = self.cfg

        # host-side block source (device=False): a per-block device round
        # trip would both waste host<->device bandwidth and pile up
        # transient HBM buffers at exactly the large sizes the
        # sharded/segmented routes exist for. stage_dtype="int8"
        # quantizes each block at staging (scale cancels in
        # eigenvectors); float stage dtypes are a plain cast.
        stage = cfg.resolved_stage_dtype()

        def host_blocks():
            from distributed_eigenspaces_tpu.data.stream import (
                stage_blocks,
            )

            return stage_blocks(
                block_stream(
                    data,
                    num_workers=cfg.num_workers,
                    rows_per_worker=cfg.rows_per_worker,
                    num_steps=cfg.num_steps,
                    remainder=cfg.remainder,
                    # int8 quantizes from full-precision floats inside
                    # stage_blocks; float stages cast here (no re-copy)
                    dtype=(
                        np.float32
                        if stage == jnp.dtype(jnp.int8) else stage
                    ),
                    device=False,
                ),
                stage,
            )

        if trainer == "fleet":
            # the solo fit AS a B=1 fleet program (parallel/fleet.py) —
            # the explicit override that pins fleet-vs-solo equivalence
            # through the public API (fleet serving's correctness
            # contract), and the path a caller who will ALSO serve
            # fleet traffic uses so solo and fleet results come from
            # the same compiled cores
            from distributed_eigenspaces_tpu.parallel.fleet import (
                fit_fleet,
            )

            masks = None
            if worker_masks is not None:
                masks = [_validated_masks(worker_masks, cfg.num_workers)]
            res = fit_fleet(
                cfg, [np.asarray(data, np.float32)], mesh=None,
                worker_masks=masks,
            )
            final = OnlineState(
                sigma_tilde=res.states.sigma_tilde[0],
                step=res.states.step[0],
            )
            self.state = final
            self._w = jnp.asarray(res.components[0])
            return self

        if trainer == "segmented":
            # stream windows — never materialize the full stack anywhere:
            # O(segment) host AND device memory, the route the oversized-
            # stage dispatch (> SCAN_STAGE_BYTES_MAX) relies on
            return self._fit_segmented(
                cfg, host_blocks(), worker_masks=worker_masks
            )

        if _routes_feature_whole(cfg, trainer):
            return self._fit_feature_sharded(
                cfg, trainer, host_blocks, worker_masks=worker_masks
            )

        blocks = list(host_blocks())
        if not blocks:
            raise ValueError("dataset yielded zero full steps")
        xs = np.stack(blocks)

        if trainer != "scan":
            raise ValueError(f"unknown trainer {trainer!r}")
        from distributed_eigenspaces_tpu.api.runner import make_whole_fit

        masks = None
        if worker_masks is not None:
            # §5.3 on the dense whole fit (round 5 — previously a loud
            # ValueError): the masked scan program, equivalent to the
            # per-step masked loop (tested)
            masks = _masks_for(
                _validated_masks(worker_masks, cfg.num_workers),
                xs.shape[0],
            )
        mesh = _scan_mesh(cfg)
        handle = make_whole_fit(cfg, "scan", mesh, masked=masks is not None)
        cc = self._compile_cache()
        if cc is not None and mesh is None and hasattr(handle.raw, "lower"):
            # zero-cold-start path (utils/compile_cache.py): the whole
            # scan program AOT-compiled against the staged shapes and
            # backed by the persistent store — a second process with
            # the same signature DESERIALIZES instead of compiling,
            # bit-identical (bench.py --coldstart measures the win).
            # Single-device programs only: the sharded jit owns its
            # in/out shardings and stays on the lazy path (it still
            # rides the XLA persistent cache wired by the same knob).
            # DET_CHECKIFY builds also stay lazy (no .lower there).
            from distributed_eigenspaces_tpu.utils.compile_cache import (
                config_knobs,
                make_key,
            )

            key = make_key(
                "scan_fit",
                (
                    cfg.dim, cfg.k, cfg.num_workers,
                    cfg.rows_per_worker, int(xs.shape[0]),
                    masks is not None,
                ),
                str(xs.dtype),
                knobs=config_knobs(cfg),
            )
            state_sds = jax.eval_shape(handle.init_state)
            xs_sds = jax.ShapeDtypeStruct(xs.shape, xs.dtype)
            if masks is not None:
                masks_j = jnp.asarray(masks, jnp.float32)
                compiled = cc.get_or_build(
                    key,
                    lambda: handle.raw.lower(
                        state_sds, xs_sds,
                        jax.ShapeDtypeStruct(
                            masks_j.shape, masks_j.dtype
                        ),
                    ),
                )
                final = compiled(
                    handle.init_state(), jnp.asarray(xs), masks_j
                )[0]
            else:
                compiled = cc.get_or_build(
                    key, lambda: handle.raw.lower(state_sds, xs_sds)
                )
                final = compiled(handle.init_state(), jnp.asarray(xs))[0]
        else:
            final = handle.fit(
                handle.init_state(), xs, worker_masks=masks
            )
        return self._finish_dense(cfg, final)

    def _fit_feature_sharded(
        self, cfg, trainer: str, host_blocks, worker_masks=None
    ) -> "OnlineDistributedPCA":
        """Feature-sharded whole fits (exact scan / Nystrom sketch) over
        the ``(workers, features)`` mesh. Two execution modes of the SAME
        trainer: a schedule that fits the per-device staging budget (and
        needs no checkpoints) stages once and runs one program; otherwise
        the windowed entry streams ``(S, m, n, d)`` windows — O(window)
        host AND device memory, a committed checkpoint per window — so
        oversized or checkpointed large-d fits run instead of raising
        (round-3 advisor finding + verdict item 3). ``worker_masks``
        (a ``(T, m)`` sequence) threads the §5.3 fault exclusion through
        the masked whole-fit programs; its length must cover the step
        schedule (short masks raise — never a silently dropped step)."""
        import warnings

        from distributed_eigenspaces_tpu.api.runner import make_whole_fit
        from distributed_eigenspaces_tpu.parallel.feature_sharded import (
            auto_feature_mesh,
        )

        if trainer == "sketch" and self.trainer == "auto":
            # results above the d*k crossover are the Nystrom sketch
            # (bounded, tested drift — tests/test_sketch_drift.py), not
            # the exact online estimate; say so once instead of letting
            # the default config silently change result semantics
            # (round-3 advisor finding). trainer_used_ records it too.
            warnings.warn(
                f"auto dispatch picked the Nystrom-sketch trainer for "
                f"d*k = {cfg.dim * cfg.k} >= {SKETCH_DK_CROSSOVER} "
                "(the measured-fastest large-d path; drift vs the exact "
                "online estimate is bounded). Pass trainer='step' for "
                "the exact estimate, and see estimator.trainer_used_.",
                stacklevel=3,
            )

        mesh = auto_feature_mesh(cfg)
        fit = make_whole_fit(
            cfg, "sketch" if trainer == "sketch" else "fs_scan", mesh
        )
        if trainer == "sketch":
            # cache for the online continuation path (fit_stream /
            # partial_fit on the SketchState this fit leaves behind)
            self._sketch_fit = fit

        # the (B, m, n, d) stack shards over BOTH mesh axes, so the
        # budget that matters is PER DEVICE — computed from the config
        # BEFORE any host materialization (the round-3 advisor finding:
        # the old check stacked the whole dataset on host, then raised)
        budget_steps = _budget_steps(cfg, mesh.devices.size)

        if worker_masks is not None:
            worker_masks = _validated_masks(worker_masks, cfg.num_workers)

        def masks_for(t):
            if worker_masks is None:
                return None
            return _masks_for(worker_masks, t)

        if self.checkpoint_dir is None and cfg.num_steps <= budget_steps:
            blocks = list(host_blocks())
            if not blocks:
                raise ValueError("dataset yielded zero full steps")
            xs = np.stack(blocks)
            state = fit.fit(
                fit.init_state(),
                jax.device_put(xs, fit.blocks_sharding),
                worker_masks=masks_for(xs.shape[0]),
            )
        else:
            windows, on_segment = self._windowed_source(
                cfg, host_blocks(), budget_steps,
                place=lambda w: jax.device_put(w, fit.blocks_sharding),
            )
            mask_windows = None
            if worker_masks is not None:
                # surplus mask rows ignored, short masks raise via
                # masks_for — the staged mode's exact contract
                windows, mask_windows = _lockstep_mask_windows(
                    windows,
                    lambda start, s: masks_for(start + s)[start:],
                )
            state = fit.fit_windows(
                fit.init_state(), windows, on_segment=on_segment,
                worker_masks=mask_windows,
            )
            if int(state.step) == 0:
                raise ValueError("dataset yielded zero full steps")

        self.state = state
        self._w = fit.extract(state)
        return self

    def _windowed_source(self, cfg, host_blocks, budget_steps, *, place):
        """ONE copy of the windowed-fit wiring shared by the segmented and
        feature-sharded routes: clamp the window to the staging budget
        (with the default segment of 50 a big schedule would stage (near)
        everything in the first window, recreating the OOM the routing
        exists to prevent), commit a rotated Checkpointer checkpoint per
        window when checkpointing (the crash-safe ``step_{t}`` layout the
        CLI resume reads — never a hand-rolled single dir), and overlap
        window t+1's host stack (+ transfer, when ``place`` stages it)
        with window t's device program via a depth-1 prefetch.

        Returns ``(windows, on_segment)`` for ``fit_windows``.
        """
        from distributed_eigenspaces_tpu.data.bin_stream import (
            window_stream,
        )

        seg = max(1, min(self.segment, budget_steps))
        on_segment = None
        if self.checkpoint_dir is not None:
            from distributed_eigenspaces_tpu.utils.checkpoint import (
                Checkpointer,
            )

            ckpt = Checkpointer(
                self.checkpoint_dir, every=1,
                rows_per_step=cfg.num_workers * cfg.rows_per_worker,
            )
            on_segment = ckpt.on_step
        windows = window_stream(host_blocks, seg)
        if cfg.prefetch_depth > 0:
            # depth 1: windows are the big unit here — one in flight
            # already overlaps the pipeline without tripling host memory
            from distributed_eigenspaces_tpu.runtime.prefetch import (
                prefetch_stream,
            )

            windows = prefetch_stream(windows, depth=1, place=place)
        return windows, on_segment

    def _fit_segmented(
        self, cfg, host_blocks, worker_masks=None
    ) -> "OnlineDistributedPCA":
        """Segmented whole-fit over a HOST block iterator: windows of
        ``segment`` steps staged on device one at a time (fit_windows) —
        O(segment) host and device memory, checkpoint every window.
        ``worker_masks`` (a (T, m) sequence) runs the masked window
        programs in data-window lockstep — §5.3 on the out-of-core
        route too (round 5)."""
        from distributed_eigenspaces_tpu.api.runner import make_whole_fit

        # place=identity: the segmented programs take host windows
        # directly, so only the host-side prep needs overlapping
        windows, on_segment = self._windowed_source(
            cfg, host_blocks, _budget_steps(cfg), place=lambda w: w,
        )
        mask_windows = None
        if worker_masks is not None:
            worker_masks = _validated_masks(worker_masks, cfg.num_workers)
            windows, mask_windows = _lockstep_mask_windows(
                windows,
                lambda start, s: _masks_for(worker_masks, start + s)[start:],
            )
        handle = make_whole_fit(
            cfg, "segmented", _scan_mesh(cfg), segment=self.segment
        )
        state = handle.fit_windows(
            handle.init_state(),
            windows,
            on_segment=on_segment,
            worker_masks=mask_windows,
        )
        if int(state.step) == 0:
            raise ValueError("dataset yielded zero full steps")
        return self._finish_dense(
            cfg, OnlineState(sigma_tilde=state.sigma_tilde, step=state.step)
        )

    def _finish_dense(self, cfg, final: OnlineState) -> "OnlineDistributedPCA":
        from distributed_eigenspaces_tpu.api.runner import extract_dense

        self.state = final
        cc = self._compile_cache()
        if cc is not None and isinstance(
            getattr(final.sigma_tilde, "sharding", None),
            jax.sharding.SingleDeviceSharding,
        ):
            # the extraction as ONE cached program instead of ~10^2
            # eager dispatches: same extract_dense definition under
            # jit, AOT-keyed like the fit (bitwise identical to the
            # eager chain — pinned in tests), so a warm process skips
            # the eager per-op compile walk too. Single-device states
            # only: a mesh-fit sigma_tilde carries a NamedSharding the
            # single-device executable would reject at call time
            from distributed_eigenspaces_tpu.utils.compile_cache import (
                config_knobs,
                make_key,
            )

            key = make_key(
                "scan_extract", (cfg.dim, cfg.k),
                str(jnp.dtype(cfg.state_dtype)),
                knobs=config_knobs(cfg),
            )
            compiled = cc.get_or_build(
                key,
                lambda: jax.jit(
                    lambda s: extract_dense(cfg, s)
                ).lower(
                    jax.ShapeDtypeStruct(
                        final.sigma_tilde.shape,
                        final.sigma_tilde.dtype,
                    )
                ),
            )
            self._w = compiled(final.sigma_tilde)
            return self
        # ONE extraction definition (api/runner.py): honors the
        # configured solver and orthonormalization
        self._w = extract_dense(cfg, final.sigma_tilde)
        return self

    def fit_stream(self, stream, *, on_step=None, worker_masks=None,
                   max_steps="auto"):
        """Fit on an iterable of pre-blocked ``(m, n, dim)`` arrays."""
        from distributed_eigenspaces_tpu.parallel.feature_sharded import (
            LowRankState,
            SketchState,
        )

        if isinstance(self.state, SketchState):
            # the Nystrom carry IS an online state (warm_step + the
            # sketch fold are per-step pure functions): continue it
            # through the trainer's windowed entry instead of refusing
            # (round-5 verdict item 3 — an online framework whose
            # fastest trainer was batch-only)
            return self._continue_sketch(
                stream, on_step=on_step, worker_masks=worker_masks,
                max_steps=max_steps,
            )
        cfg = self.cfg
        # whole_fit=False: the per-step loop never runs the sketch, so
        # the d*k crossover must not flip small-d per-step fits off the
        # exact dense state (round-4 review finding)
        if cfg.backend != "feature_sharded" and (
            resolves_feature_sharded(cfg, whole_fit=False)
            or isinstance(self.state, LowRankState)
        ):
            # two reasons to pin the backend: (a) auto at large d must
            # never reach the dense per-step path (a 12288^2 sigma_tilde
            # is the 600 MB anti-pattern this backend exists to avoid —
            # and hooks/masks routing to trainer='step' would otherwise
            # flip the backend silently); (b) a whole fit that already
            # left a rank-r carry must continue down the same backend or
            # the dense path crashes on the state shape
            cfg = cfg.replace(backend="feature_sharded")
        self.trainer_used_ = "step"
        w, state = online_distributed_pca(
            stream,
            cfg,
            pool=self.pool,
            state=self.state,
            on_step=on_step,
            worker_masks=worker_masks,
            max_steps=max_steps,
        )
        self._w, self.state = w, state
        return self

    def _continue_sketch(self, stream, *, on_step, worker_masks,
                         max_steps) -> "OnlineDistributedPCA":
        """Online continuation of a sketch-trainer fit: feed more
        ``(m, n, dim)`` blocks into the existing ``SketchState`` through
        the trainer's windowed entry (``fit_windows`` — the same
        cold-start-once contract: a restored/continued nonzero carry
        runs the all-warm continuation program, so windowed and
        incremental runs are bit-for-bit identical; pinned in
        tests/test_sketch_online.py).

        Blocks are staged ``segment`` steps per window (one compiled
        program per window); ``on_step`` forces one-step windows so the
        ``(t, state, v_bar)`` hook runs on the host between rounds —
        ``state.v`` after a one-step window IS that round's merged
        basis. ``worker_masks`` keeps the per-step contract (one mask
        row per consumed block; exhausting early raises)."""
        import itertools

        from distributed_eigenspaces_tpu.data.bin_stream import (
            window_stream,
        )

        cfg = self.cfg
        fit = self._sketch_fit
        if fit is None:
            # state restored externally (checkpoint/unpickle): rebuild
            # the same trainer the whole fit would have built
            from distributed_eigenspaces_tpu.api.runner import (
                make_whole_fit,
            )

            fit = make_whole_fit(cfg, "sketch")
            self._sketch_fit = fit

        # the per-step loop's cap semantics, EXACTLY (algo/online.py
        # _drive_stream): the cap — cfg.num_steps under "auto", the
        # given int otherwise — bounds the TOTAL step count including
        # the resumed state; "auto" is open-ended for a 1/t running
        # mean (extra rounds only improve it). A diverging
        # remaining-budget reading here would make max_steps silently
        # depend on which trainer produced the carry.
        cap = cfg.num_steps if max_steps == "auto" else max_steps
        if max_steps == "auto" and cfg.discount == "1/t":
            cap = None
        if cap is not None:
            remaining = max(0, cap - int(self.state.step))
            if remaining == 0:
                return self
            stream = itertools.islice(iter(stream), remaining)

        # continuation blocks stage exactly like the whole fit's
        # (stage_dtype honored — an int8-staged fit must not silently
        # continue at 4x the bytes; a second block dtype would also
        # compile a second trainer variant)
        from distributed_eigenspaces_tpu.data.stream import stage_blocks

        stream = stage_blocks(stream, cfg.resolved_stage_dtype())

        # window size: capped by the same per-device staging budget as
        # every other windowed path (segment=50 of an imagenet12288-
        # sized step would otherwise stage tens of GB in one window)
        budget = _budget_steps(cfg, fit.blocks_sharding.mesh.devices.size)
        seg = (
            1 if on_step is not None
            else max(1, min(self.segment, budget))
        )
        windows = window_stream(iter(stream), seg)

        mask_windows = None
        if worker_masks is not None:
            # one (m,) mask row per consumed block, taken in lockstep
            # with the data windows; exhausting early raises
            mit = iter(worker_masks)

            def take_rows(start, s):
                rows = list(itertools.islice(mit, s))
                if len(rows) < s:
                    raise ValueError(
                        "worker_masks exhausted before the stream — "
                        "every step needs its mask row"
                    )
                return np.stack(
                    [np.asarray(r, np.float32) for r in rows]
                )

            windows, mask_windows = _lockstep_mask_windows(
                windows, take_rows
            )

        on_segment = None
        if on_step is not None:
            def on_segment(steps_done, st):
                on_step(steps_done, st, st.v)

        state = fit.fit_windows(
            self.state, windows, on_segment=on_segment,
            worker_masks=mask_windows,
        )
        self.state = state
        self._w = fit.extract(state)
        self.trainer_used_ = "sketch"
        return self

    def partial_fit(self, x_blocks) -> "OnlineDistributedPCA":
        """Fold one more ``(m, n, dim)`` step into the running estimate
        (no step cap — extra online rounds past T keep refining)."""
        return self.fit_stream([jnp.asarray(x_blocks)], max_steps=None)

    def __getstate__(self):
        # the cached compiled trainer is jit-wrapped local closures —
        # unpicklable, and rebuilt lazily by _continue_sketch anyway
        # (the transform engine holds compiled executables: same story,
        # rebuilt lazily from the per-directory cache singleton)
        state = self.__dict__.copy()
        state["_sketch_fit"] = None
        state["_transform_engine"] = None
        return state

    # -- results ------------------------------------------------------------

    @property
    def components_(self) -> jax.Array:
        """(dim, k) estimated principal directions (descending order)."""
        if self._w is None:
            raise RuntimeError("call fit() first")
        return self._w

    # The reference calls this "matrix_w" (notebook cell 17-18).
    matrix_w = components_

    def transform(self, x, *, serve=None) -> jax.Array:
        """Project ``(N, dim) -> (N, k)`` (notebook cells 19-20: ``data @ W``).

        ``serve`` routes the query through a live
        ``serving.QueryServer`` instead of a local matmul: the query is
        admitted to the micro-batch queue and projected against the
        registry's LATEST published version (which may be newer than
        this estimator's own fit — that is the point of serving).
        Served and direct projections of the same version are
        bit-for-bit identical (padding a batched matmul does not change
        its rows — pinned in tests/test_serving.py).
        """
        w = self.components_  # raises before fit — the right error
        d = int(w.shape[0])
        width = np.shape(x)[-1] if np.ndim(x) >= 1 else None
        if np.ndim(x) not in (1, 2) or width != d:
            # loud beats an opaque dot_general shape error three
            # frames down (ISSUE 4 satellite; regression-tested)
            raise ValueError(
                f"transform input has feature width {width} "
                f"(shape {np.shape(x)}); this estimator was fitted "
                f"with dim={d} — pass (N, {d}) or ({d},) rows"
            )
        if serve is not None:
            z = serve.submit(np.asarray(x, np.float32)).result().z
            return jnp.asarray(z[0] if np.ndim(x) == 1 else z)
        cc = self._compile_cache()
        if cc is not None:
            # persistent-cache-backed transform kernels
            # (serving/transform.py): the bucket programs deserialize
            # in a warm process instead of compiling, and padding keeps
            # the projection bit-identical to the direct matmul below
            # (the served-vs-direct contract tests pin) — so the knob
            # changes first-call latency, never results
            if self._transform_engine is None:
                from distributed_eigenspaces_tpu.serving.transform import (
                    TransformEngine,
                )

                self._transform_engine = TransformEngine(
                    d, int(w.shape[1]), dtype=self.cfg.dtype, cache=cc
                )
            z = self._transform_engine.project(
                np.atleast_2d(np.asarray(x)), w
            )
            return z[0] if np.ndim(x) == 1 else z
        x = jnp.asarray(x, dtype=self.cfg.dtype)
        prec = jax.lax.Precision.HIGHEST if x.dtype == jnp.float32 else None
        return jnp.matmul(x, w.astype(x.dtype), precision=prec)

    def fit_transform(self, data, **kw) -> jax.Array:
        return self.fit(data, **kw).transform(data)

    def inverse_transform(self, z) -> jax.Array:
        """Back-project ``(N, k) -> (N, dim)`` (reconstruction)."""
        return jnp.asarray(z) @ self.components_.T

    def score(self, x, exact_w=None) -> dict:
        """Diagnostics: explained variance ratio on ``x``; if ``exact_w`` is
        given, worst principal angle (degrees) vs that subspace."""
        from distributed_eigenspaces_tpu.ops.linalg import (
            principal_angles_degrees,
        )

        x = jnp.asarray(x, dtype=self.cfg.dtype)
        z = x @ self.components_
        total = jnp.sum(jnp.var(x, axis=0))
        explained = jnp.sum(jnp.var(z, axis=0))
        out = {"explained_variance_ratio": float(explained / total)}
        if exact_w is not None:
            ang = principal_angles_degrees(self.components_, jnp.asarray(exact_w))
            out["max_principal_angle_deg"] = float(jnp.max(ang))
        return out
