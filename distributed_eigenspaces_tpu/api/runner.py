"""ONE constructor for the whole-fit trainers (round-5 verdict item 8).

Before this module, each whole-fit trainer was wired three times —
estimator (`api/estimator.py`), eval harness (`evals.py`), CLI
(`cli.py`) — so adding a trainer cost three copies of its construction,
state-init, and extraction logic, and the copies had already drifted
(the CLI's dense extraction passed ``orth_method``, the estimator's did
not). :func:`make_whole_fit` is the single wiring: callers name the
program kind and get a uniform handle; routing policy (WHICH kind fits a
workload) stays at the call sites, where it legitimately differs
(`choose_trainer` for the API, explicit flags for the CLI, the spec for
evals).

Handle contract::

    h = make_whole_fit(cfg, kind, mesh, seed=..., segment=..., ...)
    state  = h.init_state()
    state  = h.fit(state, blocks, idx=None, worker_masks=None)
    state  = h.fit_windows(state, windows, on_segment=..., worker_masks=...)
    w      = h.extract(state)          # (d, k), descending, canonical signs
    h.blocks_sharding                  # None on the dense single-mesh kinds

Kinds: ``"scan"`` (dense one-program fit), ``"segmented"`` (dense
windowed/checkpointable), ``"fs_scan"`` (feature-sharded exact rank-r),
``"sketch"`` (feature-sharded Nystrom).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp

from distributed_eigenspaces_tpu.config import PCAConfig

KINDS = ("scan", "segmented", "fs_scan", "sketch")


@dataclass(frozen=True)
class WholeFitHandle:
    kind: str
    fit: Callable  # (state, blocks, idx=None, worker_masks=None) -> state
    init_state: Callable[[], Any]
    extract: Callable[[Any], jax.Array]
    fit_windows: Callable | None = None
    blocks_sharding: Any = None
    #: trainer-specific extras (e.g. the sketch width) for reports
    info: dict | None = None
    #: the underlying trainer object, for trainer-specific attributes
    #: the uniform surface deliberately does not model (state_shardings,
    #: rank, ...) — specialized callers reach through, the common wiring
    #: stays shared
    raw: Any = None


def extract_dense(cfg: PCAConfig, sigma_tilde) -> jax.Array:
    """THE dense extraction: top-k of the running projector average,
    honoring the configured solver (a full d x d eigh at large d is the
    TPU anti-pattern the subspace solver exists for) AND the configured
    orthonormalization — one definition for estimator, evals and CLI
    (they had drifted on the ``orth_method`` argument).
    ``solver="distributed"`` resolves to the subspace machinery here:
    the operand is already a dense replicated d x d, so the distributed
    path has nothing to save — its crossover lives where the state is a
    factorization (``solvers.dist_extract_top_k``)."""
    from distributed_eigenspaces_tpu.ops.linalg import merged_top_k

    return merged_top_k(
        sigma_tilde, cfg.k, cfg.resolved_local_solver(),
        max(cfg.subspace_iters, 16), cfg.orth_method,
    )


def make_whole_fit(
    cfg: PCAConfig,
    kind: str,
    mesh=None,
    *,
    seed: int | None = None,
    segment: int = 50,
    gather: bool = False,
    masked: bool = False,
    supervisor=None,
) -> WholeFitHandle:
    """Build the ``kind`` whole-fit trainer as a uniform handle.

    ``mesh``: the worker mesh for the dense kinds (None = single
    device), the REQUIRED (workers, features) mesh for the
    feature-sharded kinds. ``gather``/``masked`` select the dense scan's
    staged-gather / §5.3 program variants (`algo/scan.py`);
    the feature-sharded kinds carry their masked programs internally.
    ``supervisor`` (a ``runtime.supervisor.Supervisor``) wraps the
    handle's ``fit``/``fit_windows`` entries in the retry/backoff
    policy — the whole-fit half of the self-healing layer.
    """
    if kind not in KINDS:
        raise ValueError(f"unknown whole-fit kind {kind!r}; one of {KINDS}")
    seed = cfg.seed if seed is None else seed
    if supervisor is not None:
        inner = make_whole_fit(
            cfg, kind, mesh, seed=seed, segment=segment, gather=gather,
            masked=masked,
        )
        return supervisor.wrap_handle(inner)

    if kind == "scan":
        from distributed_eigenspaces_tpu.algo.online import OnlineState
        from distributed_eigenspaces_tpu.algo.scan import make_scan_fit

        f = make_scan_fit(cfg, mesh, gather=gather, masked=masked)

        def fit(state, blocks, idx=None, worker_masks=None):
            if masked:
                if worker_masks is None:
                    raise ValueError("masked scan fit needs worker_masks")
                return f(state, blocks, jnp.asarray(worker_masks))[0]
            if worker_masks is not None:
                raise ValueError(
                    "unmasked scan handle got worker_masks; build with "
                    "masked=True"
                )
            if gather:
                return f(state, blocks, idx)[0]
            return f(state, blocks)[0]

        return WholeFitHandle(
            kind=kind,
            fit=fit,
            init_state=lambda: OnlineState.initial(
                cfg.dim, cfg.state_dtype
            ),
            extract=lambda st: extract_dense(cfg, st.sigma_tilde),
            raw=f,
        )

    if kind == "segmented":
        from distributed_eigenspaces_tpu.algo.scan import (
            SegmentState,
            make_segmented_fit,
        )

        f = make_segmented_fit(cfg, mesh, segment=segment)

        def fit(state, blocks, idx=None, worker_masks=None,
                on_segment=None):
            # on_segment: the segmented kind's checkpoint/metrics hook
            # between window programs (the other kinds run one program
            # and have no boundaries to hook). Masked segmented fits go
            # through fit_windows with pre-built (S, m) mask windows
            # (the estimator's _lockstep_mask_windows route) — a second
            # windowing implementation here would drift untested.
            if worker_masks is not None:
                raise ValueError(
                    "segmented masks run via fit_windows(worker_masks=...)"
                )
            return f(state, blocks, on_segment=on_segment)

        return WholeFitHandle(
            kind=kind,
            fit=fit,
            init_state=lambda: SegmentState.initial(
                cfg.dim, cfg.k, dtype=cfg.state_dtype
            ),
            extract=lambda st: extract_dense(cfg, st.sigma_tilde),
            fit_windows=f.fit_windows,
            info={"segment": f.segment},
            raw=f,
        )

    # feature-sharded kinds need the 2-D mesh
    if mesh is None:
        from distributed_eigenspaces_tpu.parallel.feature_sharded import (
            auto_feature_mesh,
        )

        mesh = auto_feature_mesh(cfg)

    if kind == "fs_scan":
        from distributed_eigenspaces_tpu.ops.linalg import (
            canonicalize_signs,
        )
        from distributed_eigenspaces_tpu.parallel.feature_sharded import (
            make_feature_sharded_scan_fit,
        )

        f = make_feature_sharded_scan_fit(
            cfg, mesh, seed=seed, collectives=cfg.collectives
        )
        return WholeFitHandle(
            kind=kind,
            fit=lambda state, blocks, idx=None, worker_masks=None: f(
                state, blocks,
                jnp.arange(blocks.shape[0], dtype=jnp.int32)
                if idx is None else idx,
                worker_masks=worker_masks,
            ),
            init_state=f.init_state,
            extract=lambda st: canonicalize_signs(st.u[:, : cfg.k]),
            fit_windows=f.fit_windows,
            blocks_sharding=f.blocks_sharding,
            info={"rank": f.rank},
            raw=f,
        )

    from distributed_eigenspaces_tpu.parallel.feature_sharded import (
        make_feature_sharded_sketch_fit,
    )

    f = make_feature_sharded_sketch_fit(
        cfg, mesh, seed=seed, collectives=cfg.collectives
    )
    return WholeFitHandle(
        kind="sketch",
        fit=lambda state, blocks, idx=None, worker_masks=None: f(
            state, blocks,
            jnp.arange(blocks.shape[0], dtype=jnp.int32)
            if idx is None else idx,
            worker_masks=worker_masks,
        ),
        init_state=f.init_state,
        extract=f.extract,
        fit_windows=f.fit_windows,
        blocks_sharding=f.blocks_sharding,
        info={"sketch_width": f.sketch_width},
        raw=f,
    )
