"""Public estimator API — the notebook-compatible surface (SURVEY.md §7.5)."""

from distributed_eigenspaces_tpu.api.estimator import OnlineDistributedPCA

__all__ = ["OnlineDistributedPCA"]
