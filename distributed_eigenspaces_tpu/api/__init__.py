"""Public estimator API — the notebook-compatible surface (SURVEY.md §7.5)."""

from distributed_eigenspaces_tpu.api.estimator import (
    OnlineDistributedPCA,
    choose_trainer,
)

__all__ = ["OnlineDistributedPCA", "choose_trainer"]
