"""distributed_eigenspaces_tpu — a TPU-native online distributed PCA framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of the reference
``TimeEscaper/distributed_eigenspaces`` (online distributed principal eigenspace
estimation):

- the reference's per-worker covariance + top-k eigensolve
  (``distributed.py:59-70``, ``distributed.py:22-29``) becomes XLA matmul +
  ``jnp.linalg.eigh`` / streaming subspace iteration (:mod:`.ops.linalg`);
- the RabbitMQ master/worker topology (``distributed.py:82-143``) becomes a
  :class:`~distributed_eigenspaces_tpu.parallel.WorkerPool` over a
  ``jax.sharding.Mesh``, with the projector merge exact from the d x k
factors after an ``all_gather``
  over ICI (:mod:`.parallel`);
- the notebook's online outer loop (cell 16) becomes
  :func:`~distributed_eigenspaces_tpu.algo.online_distributed_pca`, implementing
  the pseudocode exactly (:mod:`.algo`);
- the CIFAR pickle loader (``load_data.py:1-76``) is reproduced with a
  grayscale/RGB toggle plus synthetic and streaming sources (:mod:`.data`).
"""

from distributed_eigenspaces_tpu.config import PCAConfig
from distributed_eigenspaces_tpu.ops.linalg import (
    gram,
    top_k_eigvecs,
    principal_angles,
    principal_angles_degrees,
    projector,
    subspace_iteration,
)
from distributed_eigenspaces_tpu.parallel.worker_pool import WorkerPool
from distributed_eigenspaces_tpu.algo.online import (
    online_distributed_pca,
    one_shot_round,
)
from distributed_eigenspaces_tpu.api.estimator import OnlineDistributedPCA
from distributed_eigenspaces_tpu.algo.scan import make_scan_fit
from distributed_eigenspaces_tpu.algo.step import make_train_step

__version__ = "0.1.0"

__all__ = [
    "PCAConfig",
    "gram",
    "top_k_eigvecs",
    "principal_angles",
    "principal_angles_degrees",
    "projector",
    "subspace_iteration",
    "WorkerPool",
    "online_distributed_pca",
    "one_shot_round",
    "OnlineDistributedPCA",
    "make_scan_fit",
    "make_train_step",
    "__version__",
]
