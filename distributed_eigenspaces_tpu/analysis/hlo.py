"""Compiled-HLO parsing: collectives and buffer shapes, machine-checked.

The framework's multi-chip story rests on one structural claim: the
merge moves the ``(m, d, k)`` factor stack (an ``all_gather``) instead
of a ``d x d`` mean projector (a ``psum``) — 2·d/(m·k)× less ICI traffic
at the benchmark shapes (16× at d=1024, m=8, k=8) — and the
feature-sharded solvers reduce only k-wide payloads. This module makes
the claim machine-checked: parse the collectives (and, for the memory
contracts, every buffer shape) out of the COMPILED (SPMD-partitioned)
HLO, compare them against the documented model, and fail a gate if a
future change silently reintroduces a dense allreduce.

Works on the CPU virtual-device mesh (the partitioner emits the same
collective ops it would for ICI), so the audit runs in plain pytest,
inside ``dryrun_multichip``, and as CI stage 9 (``scripts/analyze.py``).

History: lived at ``utils/collectives_audit.py`` through round 9,
then behind a deprecation shim through round 12 (shim RETIRED in
ISSUE 13 — the old path no longer imports); the per-program
expectations moved from hand-rolled call sites into the contract
registry (:mod:`.contracts`), and the public names re-export from the
``analysis`` package facade.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

# one optimized-HLO collective per line. Two result forms:
#   %ag = f32[8,128,4]{...} all-gather(%p), replica_groups=...
#   %rs = (f32[64]{0}, u32[]) all-reduce-start(%p), ...   (async / tuple)
# The op-name alternation accepts the async "-start" suffix (TPU HLO
# lowers collectives to start/done pairs) and "-done" is deliberately
# NOT matched (it would double-count its start's payload).
_OP_NAMES = (
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all",
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# The tuple branch matches LAZILY up to the closing ") <op-name>(": TPU
# tiled layouts put parens INSIDE the tuple members (e.g.
# "(f32[64]{0:T(256)}, u32[])"), so a greedy-to-first-')' matcher would
# truncate mid-member and the parser-drift tripwire would raise on every
# TPU-compiled module (ADVICE.md r5).
_COLLECTIVE_RE = re.compile(
    r" = (\(.*?\)|\w+\[[\d,]*\][^ ]*) "
    r"(" + "|".join(_OP_NAMES) + r")(?:-start)?"
    r"\("
)
# raw occurrence counter for the parser-drift tripwire (see
# parse_collectives): "-done" ops and the start forms both contain the
# base name, so count call sites `name(` and `name-start(` only
_RAW_RE = re.compile(
    r"(" + "|".join(_OP_NAMES) + r")(?:-start)?\("
)

# Result-shape token at an instruction definition ("%name = SHAPE op(")
# — the per-device buffer set the memory contracts walk. Tuple results
# contribute each member via _SHAPE_RE over the matched text.
_RESULT_RE = re.compile(
    r"%[\w.\-]+ = (\([^=]*?\)|\w+\[[\d,]*\][^ ]*) \w[\w\-]*\("
)

# Itemsizes for every dtype the HLO printer emits. Unknown dtypes used
# to fall back to 4 bytes silently (and a KeyError in strict callers) —
# now any dtype outside this table raises AuditParseError naming the
# offending HLO line, so a new XLA dtype widens the table instead of
# silently mis-weighing payload bounds (ISSUE 10 satellite).
_ITEMSIZE = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "f8e4m3": 1, "f8e4m3fn": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2": 1, "f8e5m2fnuz": 1, "f8e3m4": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "s4": 1, "u4": 1, "s2": 1, "u2": 1,
    "pred": 1, "c64": 8, "c128": 16,
    "token": 0,  # sequencing tokens carry no payload
}


class AuditParseError(RuntimeError):
    """The HLO text contains something the audit cannot weigh — an
    unknown dtype or a collective call site the structured regex cannot
    parse. Loud by design: an audit that guesses is an audit that can
    read "no dense collectives" off a module it never understood."""


def itemsize_of(dtype: str, *, context: str = "") -> int:
    """Bytes per element for an HLO dtype token, or a loud
    :class:`AuditParseError` naming the dtype and the offending HLO
    line for anything outside the table."""
    try:
        return _ITEMSIZE[dtype]
    except KeyError:
        raise AuditParseError(
            f"unknown HLO dtype {dtype!r} — the audit cannot weigh its "
            f"payload; add it to analysis.hlo._ITEMSIZE"
            + (f" (offending HLO: {context.strip()!r})" if context else "")
        ) from None


@dataclass(frozen=True)
class CollectiveOp:
    op: str  # all-gather / all-reduce / ...
    dtype: str
    shape: tuple[int, ...]
    #: the HLO source line the op was parsed from — error context for
    #: unknown dtypes and contract-violation messages
    line: str = field(default="", compare=False)

    @property
    def elems(self) -> int:
        return math.prod(self.shape) if self.shape else 1

    @property
    def payload_bytes(self) -> int:
        return self.elems * itemsize_of(self.dtype, context=self.line)


def _line_around(text: str, pos: int) -> str:
    start = text.rfind("\n", 0, pos) + 1
    end = text.find("\n", pos)
    return text[start: end if end >= 0 else len(text)]


def parse_collectives(hlo_text: str) -> list[CollectiveOp]:
    """Every collective op in an (optimized, SPMD-partitioned) HLO dump.

    Shapes are PER-DEVICE — an ``all-gather`` line's shape is its
    gathered output on each device. Tuple-shaped results (async
    ``-start`` forms, combined collectives) contribute the LARGEST
    member as the op's shape — the quantity the dense tripwire checks —
    and a tripwire guards the parser itself: if the text contains more
    collective call sites than the structured regex matched, the parser
    has drifted from the HLO syntax and raises instead of silently
    under-reporting (an empty parse must never read as "no dense
    collectives"). Ops inside a ``while`` body (the ``lax.scan`` steps)
    appear once in the text; callers reason per step, which is exactly
    the granularity the byte model wants.
    """
    out = []
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shapes_txt, op = m.groups()
        line = _line_around(hlo_text, m.start())
        members = [
            (dt, tuple(int(s) for s in dims.split(",") if s))
            for dt, dims in _SHAPE_RE.findall(shapes_txt)
        ]
        if not members:
            members = [("f32", ())]  # shapeless scalar result
        dtype, dims = max(
            members, key=lambda p: math.prod(p[1]) if p[1] else 1
        )
        out.append(CollectiveOp(op=op, dtype=dtype, shape=dims, line=line))
    raw = len(_RAW_RE.findall(hlo_text))
    if raw > len(out):
        raise AuditParseError(
            f"collective parser drift: {raw} collective call sites in "
            f"the HLO but only {len(out)} parsed — the audit would "
            "under-report; fix _COLLECTIVE_RE for the new syntax"
        )
    return out


def parse_buffer_shapes(
    hlo_text: str,
) -> list[tuple[str, tuple[int, ...], str]]:
    """Every instruction-result buffer in the HLO as ``(dtype, shape,
    line)`` — PER-DEVICE shapes in a partitioned module. Tuple results
    contribute each member. This is the buffer set the memory contracts
    scan for dense ``d x d`` temporaries; over-collection is harmless
    (a shape only appears because some buffer has it), silent
    under-collection is not — instruction definitions the regex cannot
    shape-parse simply carry no digits and match nothing, and the
    collectives path has its own drift tripwire."""
    out: list[tuple[str, tuple[int, ...], str]] = []
    for m in _RESULT_RE.finditer(hlo_text):
        line = _line_around(hlo_text, m.start())
        for dt, dims in _SHAPE_RE.findall(m.group(1)):
            out.append(
                (dt, tuple(int(s) for s in dims.split(",") if s), line)
            )
    return out


def audit_compiled(compiled) -> dict:
    """Summary of a compiled program's collectives: per-(op, dtype,
    shape) counts plus the largest single payload — the number the
    dense-allreduce tripwire checks. Accepts a
    ``jit(...).lower(...).compile()`` result or its HLO text."""
    hlo_text = compiled if isinstance(compiled, str) else compiled.as_text()
    ops = parse_collectives(hlo_text)
    counts: dict[str, int] = {}
    for o in ops:
        key = f"{o.op} {o.dtype}[{','.join(map(str, o.shape))}]"
        counts[key] = counts.get(key, 0) + 1
    return {
        "ops": counts,
        "n_collectives": len(ops),
        "max_payload_elems": max((o.elems for o in ops), default=0),
        "max_payload_bytes": max(
            (o.payload_bytes for o in ops), default=0
        ),
        "_parsed": ops,
    }


def assert_no_dense_collective(audit: dict, dim: int) -> None:
    """The regression tripwire: no collective payload may reach ``d^2``
    elements (or even half of it) — the structural invariant every
    sharded trainer maintains is that ONLY factor stacks (m·d·k) and
    k-wide reductions cross the mesh, never a dense d x d matrix. A
    reintroduced dense-projector psum trips this immediately."""
    limit = dim * dim // 2
    worst = audit["max_payload_elems"]
    if worst >= limit:
        offenders = [
            f"{o.op} {o.dtype}{list(o.shape)}"
            for o in audit["_parsed"]
            if o.elems >= limit
        ]
        raise AssertionError(
            f"dense collective detected: payload {worst} elems >= "
            f"d^2/2 = {limit} ({', '.join(offenders)}) — the merge must "
            "move factors, not d x d matrices (ops/linalg.py "
            "merged_top_k_lowrank; BASELINE.md item 4)"
        )


def ici_step_model(
    m: int, d: int, k: int, *,
    n_workers_mesh: int, n_feature_shards: int = 1, itemsize: int = 4,
) -> dict:
    """Documented per-step ICI byte model for the sharded trainers,
    ring-collective accounting (what XLA lowers to on a torus):

    - factor merge: ``all_gather`` of per-device ``(m/W, d_l, k)`` shards
      into ``(m, d_l, k)`` on each of W worker-mesh devices — each
      device moves ``(W-1)/W * m * d_l * k`` elements per step
      (``d_l = d / n_feature_shards``);
    - the dense alternative this design replaces: ``psum`` of a
      ``d x d`` projector — ``2 * (W-1)/W * d^2`` elements per device;
    - feature-axis reductions (sharded matvec / CholeskyQR Grams /
      sketch folds): k-wide payloads, O(n·k + k^2) elements — reported
      as a bound, not enumerated (each is <= the merge payload by
      construction; the audit asserts the ceiling).

    Returns modeled bytes/device/step for the factor route, the dense
    route, and their ratio — the number BASELINE.md's "16x less ICI
    traffic" claim quotes, now computed instead of asserted in prose.
    """
    w = max(n_workers_mesh, 1)
    d_local = d // max(n_feature_shards, 1)
    ring = (w - 1) / w if w > 1 else 0.0
    factor = ring * m * d_local * k * itemsize
    dense = 2.0 * ring * d * d * itemsize
    return {
        "factor_gather_bytes_per_step": int(factor),
        "dense_psum_bytes_per_step": int(dense),
        # None (not inf) when the worker axis is trivial — a 1-chip mesh
        # moves nothing, and inf is not valid strict JSON
        "dense_over_factor": (
            round(dense / factor, 2) if factor else None
        ),
        "model": "ring collectives: all_gather (W-1)/W*payload, "
                 "psum 2*(W-1)/W*payload, per device per step",
    }


def scaling_projection(
    m: int, d: int, k: int, *, step_seconds: float,
    n_workers_mesh: int, n_feature_shards: int = 1,
    ici_gbps: float = 90.0,
) -> dict:
    """ICI-bytes-per-step vs step-time projection: at what mesh size
    does the merge's collective stop hiding behind the step's compute?
    ``ici_gbps`` defaults to a single v5e ICI link's ~90 GB/s (4800
    Gbps bidirectional across 4 links per chip / conservative per-link
    share); the point of the field is the RATIO trend, not the last
    percent — both inputs are in the JSON so readers can re-anchor.
    """
    model = ici_step_model(
        m, d, k,
        n_workers_mesh=n_workers_mesh,
        n_feature_shards=n_feature_shards,
    )
    wire_s = model["factor_gather_bytes_per_step"] / (ici_gbps * 1e9)
    return {
        **model,
        "assumed_ici_gb_per_sec": ici_gbps,
        "modeled_collective_seconds_per_step": round(wire_s, 9),
        "measured_step_seconds": round(step_seconds, 9),
        "collective_fraction_of_step": (
            round(wire_s / step_seconds, 6) if step_seconds > 0 else None
        ),
    }
