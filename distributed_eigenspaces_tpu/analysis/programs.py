"""The audited program matrix: build (lower + compile) every program
kind the system ships, at audit-sized shapes, on the 8-virtual-device
CPU mesh — the same partitioner that drives ICI, so the SPMD HLO the
contracts read here is the schedule a TPU pod would run.

Audit shapes are deliberately small (compile time is CI stage-9 budget)
and deliberately keep every non-feature dimension below the dense
threshold — see the premise note in :mod:`.contracts`. The matrix
covers the config surface the contracts guard: solo/fleet/serve x
pipeline x merge_interval x sharded (ISSUE 10).

Declaring a new program = one ``_register`` entry here naming its
contract; ``scripts/analyze.py --list`` shows the live matrix.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable

from distributed_eigenspaces_tpu.analysis.contracts import ProgramParams

# audit shapes: d=64 solo/fleet/serve, d=128 over 2 feature shards
# (d_local=64); everything else well below 64
_D, _K, _M, _N, _T = 64, 2, 4, 8, 3
_FEAT_D = 128
_FLEET_B = 8
_SERVE_ROWS = 16
# Pallas kernel-audit shapes (ISSUE 17): LARGE enough that a
# full-operand block is distinguishable from a tile (at the serve
# matrix's d=64 every legal block IS the full array, so the tile
# budget could never fire) — and explicit sub-maximal blocks so the
# legit programs sit far under the 131072-elem budget the mutant's
# full (rows, d) block (262144 elems) trips
_PALLAS_D, _PALLAS_ROWS, _PALLAS_K, _PALLAS_F = 1024, 256, 8, 32
_PALLAS_BR, _PALLAS_BD = 64, 128


def require_mesh_devices(n: int = 8) -> None:
    """The audit needs the virtual-device mesh. Loud, named failure
    when the interpreter booted without it (the XLA flag must be set
    before the first jax import — scripts/analyze.py and
    tests/conftest.py both do)."""
    import jax

    have = len(jax.devices())
    if have < n:
        raise RuntimeError(
            f"program audit needs >= {n} devices, found {have}: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "the first jax import (scripts/analyze.py does this; in "
            "pytest, tests/conftest.py does)"
        )


@dataclass
class BuiltProgram:
    """One audited program: the jitted callable + its abstract args,
    with the lowered/compiled artifacts cached lazily so a
    collectives-only question never pays a compile twice."""

    name: str
    contract: str  # key into contracts.CONTRACTS
    params: ProgramParams
    jitted: Any
    args: tuple
    _cache: dict = field(default_factory=dict, repr=False)

    def lowered(self):
        if "lowered" not in self._cache:
            self._cache["lowered"] = self.jitted.lower(*self.args)
        return self._cache["lowered"]

    def compiled(self):
        if "compiled" not in self._cache:
            self._cache["compiled"] = self.lowered().compile()
        return self._cache["compiled"]

    def hlo_text(self) -> str:
        return self.compiled().as_text()

    def jaxpr(self):
        if "jaxpr" not in self._cache:
            self._cache["jaxpr"] = self.jitted.trace(*self.args).jaxpr
        return self._cache["jaxpr"]

    def memory_stats(self):
        if "memory" not in self._cache:
            try:
                self._cache["memory"] = self.compiled().memory_analysis()
            except Exception:  # backend without the query — metrics only
                self._cache["memory"] = None
        return self._cache["memory"]


def _cfg(**kw):
    from distributed_eigenspaces_tpu.config import PCAConfig

    base = dict(
        dim=_D, k=_K, num_workers=_M, rows_per_worker=_N, num_steps=_T,
        solver="subspace", subspace_iters=2, warm_start_iters=1,
        compute_dtype="bfloat16",
    )
    base.update(kw)
    return PCAConfig(**base)


def _ensure_jit(fn):
    """Builders in the trainer family return jitted callables; the
    masked/feature variants return plain wrappers — normalize so every
    audited program exposes ``.lower``/``.trace``."""
    import jax

    return fn if hasattr(fn, "lower") else jax.jit(fn)


def _scan_program(name: str, *, masked: bool = False, **cfg_kw):
    def build() -> BuiltProgram:
        import jax.numpy as jnp

        from distributed_eigenspaces_tpu.algo.online import OnlineState
        from distributed_eigenspaces_tpu.algo.scan import make_scan_fit
        from distributed_eigenspaces_tpu.parallel.mesh import make_mesh

        require_mesh_devices()
        cfg = _cfg(**cfg_kw)
        mesh = make_mesh(num_workers=_M)
        fit = _ensure_jit(make_scan_fit(cfg, mesh, masked=masked))
        x = jnp.zeros((_T, _M, _N, _D), jnp.bfloat16)
        args = (OnlineState.initial(_D), x)
        if masked:
            args += (jnp.ones((_T, _M), jnp.float32),)
        return BuiltProgram(
            name=name, contract="scan_fit",
            params=ProgramParams(
                d=_D, k=_K, m=_M, n=_N, T=_T, n_workers_mesh=_M,
            ),
            jitted=fit, args=args,
        )

    return build


def _tree_program(
    name: str, *, masked: bool = False, wire: dict | None = None
):
    """Tiered-mesh tree fit (ISSUE 12): a 2x2 chip/host topology over
    the 8-device rig (4 workers on a ("host", "chip") mesh) — the
    tree_merge contract's subject. The tree's whole point shows in the
    bound: max(d*k, (f*k)^2) = 128 elems here vs the flat factor
    stack's m*d*k = 512. ``wire`` (ISSUE 20) compiles the same fit
    under a ``merge_wire_dtype`` policy — the ``collective-wire-dtype``
    rule then audits that the declared codecs actually reach the
    partitioned HLO's data movers."""

    def build() -> BuiltProgram:
        import jax.numpy as jnp

        from distributed_eigenspaces_tpu.algo.online import OnlineState
        from distributed_eigenspaces_tpu.algo.scan import make_scan_fit
        from distributed_eigenspaces_tpu.parallel.topology import (
            make_tiered_mesh,
            resolve_topology,
        )
        from distributed_eigenspaces_tpu.parallel.wire import (
            resolve_wire_policy,
        )

        require_mesh_devices()
        cfg = _cfg(
            merge_topology=(("chip", 2), ("host", 2)),
            merge_wire_dtype=wire,
        )
        topo = resolve_topology(cfg)
        mesh = make_tiered_mesh(topo)
        fit = _ensure_jit(make_scan_fit(cfg, mesh, masked=masked))
        x = jnp.zeros((_T, _M, _N, _D), jnp.bfloat16)
        args = (OnlineState.initial(_D), x)
        if masked:
            args += (jnp.ones((_T, _M), jnp.float32),)
        return BuiltProgram(
            name=name, contract="tree_merge",
            params=ProgramParams(
                d=_D, k=_K, m=_M, n=_N, T=_T, n_workers_mesh=_M,
                tier_fan_ins=topo.fan_ins, tier_axes=topo.names,
                tier_wire_dtypes=resolve_wire_policy(cfg, topo) or (),
            ),
            jitted=fit, args=args,
        )

    return build


def _feature_program(name: str, kind: str):
    def build() -> BuiltProgram:
        import jax
        import jax.numpy as jnp

        from distributed_eigenspaces_tpu.parallel.feature_sharded import (
            auto_feature_mesh,
            make_feature_sharded_scan_fit,
            make_feature_sharded_sketch_fit,
        )

        require_mesh_devices()
        cfg = _cfg(num_workers=_M, dim=_FEAT_D, backend="feature_sharded")
        mesh = auto_feature_mesh(cfg)
        mk = (
            make_feature_sharded_scan_fit if kind == "scan"
            else make_feature_sharded_sketch_fit
        )
        fit = mk(cfg, mesh, seed=0)
        blocks = jax.device_put(
            jnp.zeros((3, _M, _N, _FEAT_D), jnp.bfloat16),
            fit.blocks_sharding,
        )
        idx = jnp.arange(2 * _T, dtype=jnp.int32) % 3
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        return BuiltProgram(
            name=name, contract="feature_sharded",
            params=ProgramParams(
                d=_FEAT_D, k=_K, m=_M, n=_N, T=2 * _T,
                n_feature_shards=axes.get("features", 1),
                n_workers_mesh=axes.get("workers", 1),
                sketch_width=int(getattr(fit, "sketch_width", 0) or 0),
            ),
            jitted=_ensure_jit(lambda s, b, i: fit(s, b, i)),
            args=(fit.init_state(), blocks, idx),
        )

    return build


def _fleet_program(name: str, *, masked: bool = False):
    def build() -> BuiltProgram:
        import jax.numpy as jnp

        from distributed_eigenspaces_tpu.parallel.fleet import (
            fleet_mesh,
            init_fleet_states,
            make_fleet_fit,
        )

        require_mesh_devices()
        cfg = _cfg()
        mesh = fleet_mesh(_FLEET_B)
        fit = _ensure_jit(make_fleet_fit(cfg, mesh, masked=masked))
        xs = jnp.zeros((_FLEET_B, _T, _M, _N, _D), jnp.bfloat16)
        actives = jnp.ones((_FLEET_B, _T), jnp.float32)
        args = (init_fleet_states(cfg, _FLEET_B), xs)
        if masked:
            args += (jnp.ones((_FLEET_B, _T, _M), jnp.float32),)
        args += (actives,)
        return BuiltProgram(
            name=name, contract="fleet_fit",
            params=ProgramParams(
                d=_D, k=_K, m=_M, n=_N, T=_T, B=_FLEET_B,
                n_workers_mesh=_FLEET_B,
            ),
            jitted=fit, args=args,
        )

    return build


def _dist_merge_program(name: str):
    """The distributed MERGE solve (ISSUE 15): dist_merged_top_k on
    the (workers, features) mesh at audit shapes — the crossover twin
    of the feature-sharded exact merge. The dist_solve contract's
    subject: the worker factor-stack gather plus k-wide feature psums
    only, output a (d_local, k) row shard."""

    def build() -> BuiltProgram:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_eigenspaces_tpu.parallel.mesh import (
            FEATURE_AXIS,
            WORKER_AXIS,
            make_mesh,
            shard_map,
        )
        from distributed_eigenspaces_tpu.solvers import dist_merged_top_k

        require_mesh_devices()
        mesh = make_mesh(num_workers=_M, num_feature_shards=2)

        def merge(vws, mask):
            return dist_merged_top_k(vws, _K, mask=mask, iters=2)

        in_specs = (P(WORKER_AXIS, FEATURE_AXIS, None), P(WORKER_AXIS))
        fit = jax.jit(
            shard_map(
                merge, mesh=mesh, in_specs=in_specs,
                out_specs=P(FEATURE_AXIS, None), check_vma=False,
            ),
            in_shardings=tuple(
                NamedSharding(mesh, s) for s in in_specs
            ),
        )
        args = (
            jax.ShapeDtypeStruct((_M, _FEAT_D, _K), jnp.float32),
            jax.ShapeDtypeStruct((_M,), jnp.float32),
        )
        return BuiltProgram(
            name=name, contract="dist_solve",
            params=ProgramParams(
                d=_FEAT_D, k=_K, m=_M, n_feature_shards=2,
                n_workers_mesh=_M,
            ),
            jitted=fit, args=args,
        )

    return build


def _dist_extract_program(name: str):
    """The distributed SERVING extract (ISSUE 15): dist_extract_top_k
    of the running low-rank state U diag(s) U^T from its row-sharded
    factors — the publish-time solve above the crossover whose output
    basis is born sharded."""

    _R = 8  # audit state rank (the operator's factor width)

    def build() -> BuiltProgram:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_eigenspaces_tpu.parallel.mesh import (
            FEATURE_AXIS,
            make_mesh,
            shard_map,
        )
        from distributed_eigenspaces_tpu.solvers import dist_extract_top_k

        require_mesh_devices()
        mesh = make_mesh(num_workers=_M, num_feature_shards=2)

        def extract(u, s):
            return dist_extract_top_k(u, s, _K, iters=2)

        in_specs = (P(FEATURE_AXIS, None), P())
        fit = jax.jit(
            shard_map(
                extract, mesh=mesh, in_specs=in_specs,
                out_specs=P(FEATURE_AXIS, None), check_vma=False,
            ),
            in_shardings=tuple(
                NamedSharding(mesh, s) for s in in_specs
            ),
        )
        args = (
            jax.ShapeDtypeStruct((_FEAT_D, _R), jnp.float32),
            jax.ShapeDtypeStruct((_R,), jnp.float32),
        )
        return BuiltProgram(
            name=name, contract="dist_solve",
            params=ProgramParams(
                d=_FEAT_D, k=_K, m=1, n_feature_shards=2,
                n_workers_mesh=_M, sketch_width=_R,
            ),
            jitted=fit, args=args,
        )

    return build


def _deflation_merge_program(name: str):
    """The parallel-deflation solve (ISSUE 18): dist_deflation_eig on
    the (components, features) mesh — k eigenvector lanes
    model-parallel over ``components``, each lane iterating its
    ``(d_local, k/L)`` block against the low-rank state operator with
    deflation corrections from lower lanes. The deflation_solve
    contract's subject: the cross-lane panel gather plus k-wide
    feature psums only; the per-lane seed blocks enter SHARDED over
    ``('components', 'features')`` so the new axis is audited
    non-vacuously."""

    _R = 8  # audit state rank (the operator's factor width)
    _DK = 8  # audit k: 4 lanes x lane width 2
    _LANES = 4

    def build() -> BuiltProgram:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from distributed_eigenspaces_tpu.parallel.mesh import (
            COMPONENT_AXIS,
            FEATURE_AXIS,
            make_component_mesh,
            shard_map,
        )
        from distributed_eigenspaces_tpu.solvers import (
            dist_deflation_eig,
        )
        from distributed_eigenspaces_tpu.solvers.distributed import (
            lowrank_matvec,
        )

        require_mesh_devices()
        mesh = make_component_mesh(_LANES, 2)

        def solve(v0, u, s):
            return dist_deflation_eig(
                lowrank_matvec(u, s, FEATURE_AXIS),
                u.shape[0],
                _DK,
                lanes=_LANES,
                iters=2,
                v0=v0[0],  # this slot's (d_local, kb) seed block
            )

        in_specs = (
            P(COMPONENT_AXIS, FEATURE_AXIS, None),
            P(FEATURE_AXIS, None),
            P(),
        )
        fit = jax.jit(
            shard_map(
                solve, mesh=mesh, in_specs=in_specs,
                out_specs=P(FEATURE_AXIS, None), check_vma=False,
            ),
            in_shardings=tuple(
                NamedSharding(mesh, s) for s in in_specs
            ),
        )
        args = (
            jax.ShapeDtypeStruct(
                (_LANES, _FEAT_D, _DK // _LANES), jnp.float32
            ),
            jax.ShapeDtypeStruct((_FEAT_D, _R), jnp.float32),
            jax.ShapeDtypeStruct((_R,), jnp.float32),
        )
        return BuiltProgram(
            name=name, contract="deflation_solve",
            params=ProgramParams(
                d=_FEAT_D, k=_DK, m=1, n_feature_shards=2,
                n_workers_mesh=_LANES, sketch_width=_R,
                components=_LANES,
            ),
            jitted=fit, args=args,
        )

    return build


def _dist_serve_program(name: str, kind: str):
    """Sharded-basis serving (ISSUE 15): the engine's own lowering at
    ``basis_spec=("features", None)`` — queries shard over (workers,
    features), the basis stays a row-sharded operand, and the
    projection psum is the program's only collective."""

    def build() -> BuiltProgram:
        import jax

        from distributed_eigenspaces_tpu.parallel.mesh import make_mesh
        from distributed_eigenspaces_tpu.serving.transform import (
            TransformEngine,
        )

        require_mesh_devices()
        mesh = make_mesh(num_workers=4, num_feature_shards=2)
        eng = TransformEngine(
            _FEAT_D, _K, mesh=mesh, basis_spec=("features", None),
        )
        rows = _SERVE_ROWS
        fn, arg_like, second_shape = eng._fns[kind]
        if kind == "residual":
            second = eng._z_like(rows)
        else:
            second = jax.ShapeDtypeStruct(second_shape, jax.numpy.float32)
        lowered = eng._lowered(kind, rows)
        built = BuiltProgram(
            name=name, contract="dist_serve",
            params=ProgramParams(
                d=_FEAT_D, k=_K, rows=rows, n_feature_shards=2,
                n_workers_mesh=4,
            ),
            jitted=_ensure_jit(fn),
            args=(arg_like(rows), second),
        )
        built._cache["lowered"] = lowered
        return built

    return build


def _population_program(name: str):
    """The population cohort reduce (ISSUE 16): the hardened
    Byzantine-tolerant merge of one sampled cohort's (d, k) summaries,
    cohort-sharded over the workers axis. The population_merge
    contract's subject: ONE all-gather of the (cohort, d, k) stack —
    payload a function of the COHORT, never the population — then the
    clip / trim / screen pipeline replicated post-gather."""

    _COHORT = 16  # audit cohort: < dense_dim, and 8 | 16

    def build() -> BuiltProgram:
        import jax
        import jax.numpy as jnp

        from distributed_eigenspaces_tpu.parallel.clients import (
            make_sharded_cohort_reduce,
        )
        from distributed_eigenspaces_tpu.parallel.mesh import make_mesh

        require_mesh_devices()
        mesh = make_mesh(num_workers=8)
        cfg = _cfg(
            population=1024, cohort_size=_COHORT, max_poison_frac=0.1,
        )
        fit = make_sharded_cohort_reduce(cfg, mesh)
        args = (
            jax.ShapeDtypeStruct((_COHORT, _D, _K), jnp.float32),
            jax.ShapeDtypeStruct((_COHORT,), jnp.float32),
        )
        return BuiltProgram(
            name=name, contract="population_merge",
            params=ProgramParams(d=_D, k=_K, m=_COHORT, n_workers_mesh=8),
            jitted=fit, args=args,
        )

    return build


def _serve_program(name: str, kind: str, *, sharded: bool):
    def build() -> BuiltProgram:
        import jax

        from distributed_eigenspaces_tpu.parallel.mesh import make_mesh
        from distributed_eigenspaces_tpu.serving.transform import (
            TransformEngine,
        )

        require_mesh_devices()
        mesh = make_mesh(num_workers=8) if sharded else None
        eng = TransformEngine(_D, _K, mesh=mesh)
        rows = _SERVE_ROWS
        fn, arg_like, second_shape = eng._fns[kind]
        if kind == "residual":
            second = eng._z_like(rows)
        else:
            second = jax.ShapeDtypeStruct(second_shape, jax.numpy.float32)
        # reuse the engine's own lowering path (the audited program IS
        # the served program), wrapped so lower/trace see the args
        lowered = eng._lowered(kind, rows)
        built = BuiltProgram(
            name=name, contract="serve_transform",
            params=ProgramParams(
                d=_D, k=_K, rows=rows,
                n_workers_mesh=8 if sharded else 1,
            ),
            jitted=_ensure_jit(fn),
            args=(arg_like(rows), second),
        )
        built._cache["lowered"] = lowered
        return built

    return build


def _pallas_program(name: str, kind: str):
    """Fused serve / solver Pallas kernels (ISSUE 17), audited at the
    kernel shapes above. ``interpret=True`` so the audit compiles on
    the CPU rig — the traced ``pallas_call`` eqn carries the SAME
    kernel jaxpr and block refs the TPU lowering would, which is all
    the tile-budget pass reads."""

    def build() -> BuiltProgram:
        import jax
        import jax.numpy as jnp

        from distributed_eigenspaces_tpu.ops import pallas_gram as pg

        require_mesh_devices()
        d, rows = _PALLAS_D, _PALLAS_ROWS
        k, f = _PALLAS_K, _PALLAS_F
        br, bd = _PALLAS_BR, _PALLAS_BD
        if kind == "project_bf16":
            fn = jax.jit(lambda x, v: pg.serve_project_pallas(
                x, v, block_rows=br, block_d=bd, interpret=True,
            ))
            args = (
                jax.ShapeDtypeStruct((rows, d), jnp.float32),
                jax.ShapeDtypeStruct((d, k), jnp.float32),
            )
        elif kind == "project_i8":
            fn = jax.jit(lambda x, q, s: pg.serve_project_i8_pallas(
                x, q, s, block_rows=br, block_d=bd, interpret=True,
            ))
            args = (
                jax.ShapeDtypeStruct((rows, d), jnp.float32),
                jax.ShapeDtypeStruct((d, k), jnp.int8),
                jax.ShapeDtypeStruct((1, k), jnp.float32),
            )
        else:  # matvec_gram: the fused solver inner sweep
            fn = jax.jit(lambda c, v: pg.matvec_gram_pallas(
                c, v, block_d=bd, interpret=True,
            ))
            args = (
                jax.ShapeDtypeStruct((d, f), jnp.float32),
                jax.ShapeDtypeStruct((d, k), jnp.float32),
            )
        return BuiltProgram(
            name=name, contract="serve_pallas",
            params=ProgramParams(
                d=d, k=k, rows=rows, sketch_width=f,
            ),
            jitted=fn, args=args,
        )

    return build


#: name -> zero-arg builder. The ORDER is the report order.
PROGRAMS: dict[str, Callable[[], BuiltProgram]] = {
    # solo scan family x pipeline x merge_interval
    "scan_solo": _scan_program("scan_solo"),
    "scan_pipelined": _scan_program(
        "scan_pipelined", pipeline_merge=True
    ),
    "scan_interval2": _scan_program("scan_interval2", merge_interval=2),
    "scan_pipelined_interval2": _scan_program(
        "scan_pipelined_interval2", pipeline_merge=True, merge_interval=2
    ),
    "scan_masked": _scan_program("scan_masked", masked=True),
    "scan_masked_interval2": _scan_program(
        "scan_masked_interval2", masked=True, merge_interval=2
    ),
    # tiered-mesh tree merge (ISSUE 12)
    "tree_fit": _tree_program("tree_fit"),
    "tree_fit_masked": _tree_program("tree_fit_masked", masked=True),
    "tree_fit_wire": _tree_program(
        "tree_fit_wire", wire={"chip": "bf16", "host": "int8"}
    ),
    # feature-sharded cores
    "feature_scan": _feature_program("feature_scan", "scan"),
    "feature_sketch": _feature_program("feature_sketch", "sketch"),
    # fleet (B > 1, sharded over the workers axis)
    "fleet_b8": _fleet_program("fleet_b8"),
    "fleet_b8_masked": _fleet_program("fleet_b8_masked", masked=True),
    # serve transforms, solo and row-sharded
    "serve_project": _serve_program(
        "serve_project", "project", sharded=True
    ),
    "serve_reconstruct": _serve_program(
        "serve_reconstruct", "reconstruct", sharded=True
    ),
    "serve_residual": _serve_program(
        "serve_residual", "residual", sharded=True
    ),
    "serve_project_solo": _serve_program(
        "serve_project_solo", "project", sharded=False
    ),
    # population cohort reduce (ISSUE 16)
    "population_reduce": _population_program("population_reduce"),
    # distributed eigensolve + sharded-basis serving (ISSUE 15)
    "dist_merge": _dist_merge_program("dist_merge"),
    "dist_extract": _dist_extract_program("dist_extract"),
    # parallel-deflation eigensolve on the components axis (ISSUE 18)
    "deflation_merge": _deflation_merge_program("deflation_merge"),
    "dist_serve_project": _dist_serve_program(
        "dist_serve_project", "project"
    ),
    "dist_serve_reconstruct": _dist_serve_program(
        "dist_serve_reconstruct", "reconstruct"
    ),
    "dist_serve_residual": _dist_serve_program(
        "dist_serve_residual", "residual"
    ),
    # fused serve / solver Pallas kernels (ISSUE 17)
    "pallas_serve_project_bf16": _pallas_program(
        "pallas_serve_project_bf16", "project_bf16"
    ),
    "pallas_serve_project_i8": _pallas_program(
        "pallas_serve_project_i8", "project_i8"
    ),
    "pallas_matvec_gram": _pallas_program(
        "pallas_matvec_gram", "matvec_gram"
    ),
}

_BUILT: dict[str, BuiltProgram] = {}


def build_program(name: str) -> BuiltProgram:
    """Build (and cache) one audited program by matrix name."""
    if name not in PROGRAMS:
        raise KeyError(
            f"unknown program {name!r}; matrix: {sorted(PROGRAMS)}"
        )
    if name not in _BUILT:
        _BUILT[name] = PROGRAMS[name]()
    return _BUILT[name]


def engine_params(engine) -> ProgramParams:
    """Params for a live :class:`~..serving.transform.TransformEngine`
    — the serve-tier report audits the engine's ALREADY-COMPILED bucket
    programs (zero extra compiles)."""
    mesh = engine.mesh
    rows = 1
    n_mesh = 1
    if mesh is not None:
        n_mesh = int(math.prod(mesh.devices.shape))
    return ProgramParams(
        d=engine.d, k=engine.k, rows=rows, n_workers_mesh=n_mesh,
    )
