"""Sharding contracts: declared PartitionSpecs, checked statically.

The ROADMAP's d-ceiling leg (distributed eigensolve, sharded (d, k)
bases end-to-end) needs the "no d x d buffer" memory contract extended
to "no un-sharded (d, k) buffer" — auto-partitioned sharding is exactly
where silent replication hides (arxiv 2004.13336 argues for making the
update step's sharding EXPLICIT rather than trusting propagation). This
module is that rule as a first-class contract family:

- each :class:`~.contracts.ProgramContract` declares the
  PartitionSpecs its inputs/outputs must carry
  (:class:`DeclaredBuffer` patterns over
  :class:`~.contracts.ProgramParams` shapes);
- the checker reads the ACTUAL shardings off the compiled artifact
  (``compiled.input_shardings`` / ``output_shardings`` zipped against
  the jaxpr avals) plus the HLO ``sharding={...}`` annotations, and
  flags **silent replication** — a buffer the contract declares
  sharded over ``workers``/``features``/a tier axis that the compiled
  program holds replicated — naming the program, the buffer shape, and
  the offending HLO location;
- an intermediate-buffer floor (feature-sharded programs) additionally
  scans the per-device HLO buffer set: no device may hold a full-d
  buffer with >= 2 columns — the un-sharded (d, k) intermediate the
  distributed-solve path must never materialize.

Violations never raise; they aggregate through
:func:`~.contracts.check_program` like every other pass, so a CI
failure names program + rule + location from the message alone.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Callable

from distributed_eigenspaces_tpu.analysis import hlo as _hlo

#: dims-pattern wildcard — matches any axis strictly below the
#: program's dense threshold (so a wildcard can never swallow a d-wide
#: axis and mis-bind a declared pattern onto the wrong buffer)
WILD = None


@dataclass(frozen=True)
class DeclaredBuffer:
    """One declared buffer: a shape PATTERN (ints exact, ``WILD`` =
    any axis below the dense threshold) plus the PartitionSpec the
    compiled program must give every leaf the pattern matches.

    ``spec(params)`` entries mirror PartitionSpec: ``None`` =
    replicated dim, an axis name, or a tuple of axis names (compared
    as SETS — mesh factorings reorder tier axes freely)."""

    name: str
    role: str  # "in" | "out"
    dims: Callable[..., tuple]
    spec: Callable[..., tuple]
    #: required patterns that match no leaf are a violation (a stale
    #: contract is a claim nobody checks); optional ones simply skip
    required: bool = True


@dataclass(frozen=True)
class ShardingContract:
    """The sharding half of a program contract."""

    buffers: tuple[DeclaredBuffer, ...]
    #: per-device HLO buffers with an axis >= this floor AND >= 2
    #: remaining elements are un-sharded (d, k) intermediates — the
    #: replication the d-ceiling invariant forbids. None = no
    #: intermediate rule (dense_state programs legitimately carry d x d)
    replicated_axis_floor: Callable[..., int] | None = None
    #: at least one declared-SHARDED buffer must match a leaf, or the
    #: audit passed vacuously (was the program actually partitioned?)
    require_some: bool = True


# -- actual-sharding extraction ----------------------------------------------


def _spec_sets(entries, rank: int) -> tuple[frozenset, ...]:
    """Normalize PartitionSpec-like entries to per-dim axis-name sets,
    padded with replicated dims to ``rank``."""
    out = []
    for e in list(entries)[:rank]:
        if e is None:
            out.append(frozenset())
        elif isinstance(e, (tuple, list)):
            out.append(frozenset(str(a) for a in e))
        else:
            out.append(frozenset({str(e)}))
    while len(out) < rank:
        out.append(frozenset())
    return tuple(out)


def actual_spec_sets(sharding, shape) -> tuple[frozenset, ...] | None:
    """Per-dim axis-name sets for a compiled leaf's sharding.

    NamedShardings expose ``.spec`` directly. GSPMD shardings carry no
    axis names — fall back to per-dim partition FACTORS via
    ``shard_shape`` and mark partitioned dims with the ``"?"``
    pseudo-axis (sharded-over-something still refutes silent
    replication). None = the sharding is opaque; the caller skips the
    leaf rather than guessing."""
    rank = len(shape)
    spec = getattr(sharding, "spec", None)
    if spec is not None:
        return _spec_sets(tuple(spec), rank)
    if getattr(sharding, "is_fully_replicated", False):
        return tuple(frozenset() for _ in range(rank))
    try:
        local = sharding.shard_shape(tuple(shape))
    except Exception:
        return None
    return tuple(
        frozenset({"?"}) if loc != glob else frozenset()
        for glob, loc in zip(shape, local)
    )


def _fmt_sets(sets) -> str:
    def one(s):
        if not s:
            return "None"
        return "+".join(sorted(s))

    return "(" + ", ".join(one(s) for s in sets) + ")"


def _matches(pattern, shape, wildcard_max: int) -> bool:
    if len(pattern) != len(shape):
        return False
    for want, have in zip(pattern, shape):
        if want is WILD:
            if have >= wildcard_max:
                return False
        elif have != want:
            return False
    return True


# -- HLO annotation census ---------------------------------------------------

_ANNOT_RE = re.compile(r"sharding=\{([^{}]*(?:\{[^{}]*\}[^{}]*)*)\}")


def parse_hlo_shardings(hlo_text: str) -> dict:
    """Census of ``sharding={...}`` annotations in a compiled module:
    how many buffers the partitioner pinned replicated vs device-tiled.
    Metrics, not a gate — the leaf-level checker is the gate; this
    number is what makes "the program carries N sharded annotations"
    visible in ``analyze.py --shardings`` output."""
    n_rep = n_dev = n_other = 0
    for m in _ANNOT_RE.finditer(hlo_text):
        body = m.group(1)
        if "devices=" in body:
            n_dev += 1
        elif "replicated" in body or "maximal" in body:
            n_rep += 1
        else:
            n_other += 1
    return {
        "n_annotations": n_rep + n_dev + n_other,
        "n_replicated": n_rep,
        "n_device_tiled": n_dev,
        "n_other": n_other,
    }


def _param_location(hlo_text: str, shape) -> str:
    """The HLO parameter line holding a buffer of ``shape`` — the
    offending location a silent-replication message names."""
    token = "[" + ",".join(str(int(s)) for s in shape) + "]"
    for line in hlo_text.splitlines():
        if " parameter(" in line and token in line:
            return line.strip()
    return ""


# -- the checker -------------------------------------------------------------


def check_shardings(
    scontract: ShardingContract,
    params,
    *,
    program: str,
    dense_dim: int,
    in_avals,
    in_shardings,
    out_avals,
    out_shardings,
    hlo_text: str = "",
) -> tuple[list, dict]:
    """The sharding pass: declared PartitionSpecs vs the compiled
    artifact's actual leaf shardings + per-device HLO buffers.

    ``in_shardings``/``out_shardings`` are FLAT leaf lists aligned
    with the jaxpr avals (``jax.tree_util.tree_leaves`` of
    ``compiled.input_shardings``/``output_shardings`` — see
    :func:`check_built`). Returns ``(violations, metrics)``."""
    from distributed_eigenspaces_tpu.analysis.contracts import Violation

    viols: list = []
    detail: list[dict] = []
    n_sharded_ok = 0

    if len(in_avals) != len(in_shardings) or len(out_avals) != len(
        out_shardings
    ):
        viols.append(Violation(
            program=program,
            rule="sharding-contract",
            message=(
                f"cannot align jaxpr avals with compiled sharding "
                f"leaves (in {len(in_avals)} vs {len(in_shardings)}, "
                f"out {len(out_avals)} vs {len(out_shardings)}) — the "
                "audit would silently check the wrong buffers"
            ),
        ))
        return viols, {"checked": False, "buffers": detail}

    leaves = [
        ("in", i, tuple(int(s) for s in getattr(a, "shape", ())), sh)
        for i, (a, sh) in enumerate(zip(in_avals, in_shardings))
    ] + [
        ("out", i, tuple(int(s) for s in getattr(a, "shape", ())), sh)
        for i, (a, sh) in enumerate(zip(out_avals, out_shardings))
    ]

    for buf in scontract.buffers:
        pattern = buf.dims(params)
        want = _spec_sets(buf.spec(params), len(pattern))
        matched = 0
        for role, idx, shape, sharding in leaves:
            if role != buf.role or not _matches(
                pattern, shape, dense_dim
            ):
                continue
            matched += 1
            actual = actual_spec_sets(sharding, shape)
            row = {
                "buffer": buf.name,
                "role": role,
                "leaf": idx,
                "shape": list(shape),
                "declared": _fmt_sets(want),
                "actual": _fmt_sets(actual) if actual else "<opaque>",
                "ok": True,
            }
            detail.append(row)
            if actual is None:
                continue  # opaque sharding: nothing checkable
            loc = (
                _param_location(hlo_text, shape) if role == "in"
                else f"output leaf {idx}"
            )
            ok = True
            for dim, (w, a) in enumerate(zip(want, actual)):
                if w and not a:
                    ok = False
                    viols.append(Violation(
                        program=program,
                        rule="silent-replication",
                        message=(
                            f"{buf.name} ({role} leaf {idx}, shape "
                            f"{list(shape)}) is declared sharded over "
                            f"{sorted(w)} on dim {dim} but the "
                            "compiled program holds it REPLICATED — "
                            "an un-sharded (d, k) buffer is exactly "
                            "the regression the d-ceiling contract "
                            "forbids"
                        ),
                        location=loc,
                    ))
                elif not w and a:
                    ok = False
                    viols.append(Violation(
                        program=program,
                        rule="sharding-contract",
                        message=(
                            f"{buf.name} ({role} leaf {idx}, shape "
                            f"{list(shape)}) is declared replicated "
                            f"on dim {dim} but compiled sharded over "
                            f"{sorted(a)} — update the declared "
                            "PartitionSpec if this layout is "
                            "intentional"
                        ),
                        location=loc,
                    ))
                elif w and a and a != {"?"} and w != a:
                    ok = False
                    viols.append(Violation(
                        program=program,
                        rule="sharding-contract",
                        message=(
                            f"{buf.name} ({role} leaf {idx}, shape "
                            f"{list(shape)}) dim {dim} is sharded "
                            f"over {sorted(a)} but declared "
                            f"{sorted(w)}"
                        ),
                        location=loc,
                    ))
            row["ok"] = ok
            if ok and any(want):
                n_sharded_ok += 1
        if buf.required and matched == 0:
            viols.append(Violation(
                program=program,
                rule="sharding-contract",
                message=(
                    f"declared buffer {buf.name!r} (pattern "
                    f"{list(pattern)}, {buf.role}) matched no "
                    "compiled leaf — the sharding contract is stale; "
                    "update the declaration in analysis/contracts.py"
                ),
            ))

    if scontract.replicated_axis_floor is not None:
        floor = scontract.replicated_axis_floor(params)
        for _dtype, dims, line in _hlo.parse_buffer_shapes(hlo_text):
            if not dims:
                continue
            widest = max(dims)
            rest = math.prod(dims) // widest
            if widest >= floor and rest >= 2:
                viols.append(Violation(
                    program=program,
                    rule="silent-replication",
                    message=(
                        f"per-device HLO buffer {list(dims)} holds a "
                        f"full-width axis (>= {floor}) with {rest} "
                        "companion elements — an un-sharded (d, k) "
                        "intermediate materialized on one device"
                    ),
                    location=line.strip(),
                ))

    if scontract.require_some and n_sharded_ok == 0 and not viols:
        viols.append(Violation(
            program=program,
            rule="sharding-contract",
            message=(
                "no declared-sharded buffer matched any compiled "
                "leaf — the sharding audit passed vacuously (was the "
                "program actually partitioned?)"
            ),
        ))

    metrics = {
        "checked": True,
        "n_declared": len(scontract.buffers),
        "n_sharded_ok": n_sharded_ok,
        "buffers": detail,
        "annotations": parse_hlo_shardings(hlo_text),
    }
    return viols, metrics


def check_built(built, contract) -> tuple[list, dict]:
    """The sharding pass over one BuiltProgram: reads the compiled
    artifact's input/output shardings (zero extra compiles — the
    contract passes already compiled it). Unsharded programs
    (``n_workers_mesh <= 1``, e.g. the solo serve transform) are
    skipped with a named reason rather than checked against specs
    that assume a mesh."""
    import jax

    params = built.params
    scontract = getattr(contract, "sharding", None)
    if scontract is None:
        return [], {"checked": False, "reason": "no sharding contract"}
    if params.n_workers_mesh <= 1:
        return [], {"checked": False, "reason": "unsharded program"}
    compiled = built.compiled()
    jaxpr = built.jaxpr()
    return check_shardings(
        scontract, params,
        program=built.name,
        dense_dim=contract.dense_dim(params),
        in_avals=list(jaxpr.in_avals),
        in_shardings=jax.tree_util.tree_leaves(
            compiled.input_shardings
        ),
        out_avals=list(jaxpr.out_avals),
        out_shardings=jax.tree_util.tree_leaves(
            compiled.output_shardings
        ),
        hlo_text=built.hlo_text(),
    )
