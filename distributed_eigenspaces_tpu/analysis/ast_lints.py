"""AST lints (passes 3b and 4): host-sync calls inside jitted code
paths, and the repo's lock discipline over the threaded runtime.

Both linters work on SOURCE TEXT (``lint_*_source``) so the mutation
self-tests can feed seeded-violation fixtures without touching the
tree; the ``lint_*`` wrappers walk the real target files.

**Host-sync lint.** A jit-traced function that calls ``.item()`` /
``float()`` / ``np.asarray()`` on a traced value, or branches in
Python on one, forces a device->host sync (or a trace error) in the
middle of a compiled region — the exact dispatch stalls the scan
trainers exist to eliminate. Traced functions are found statically:
``@jax.jit`` / ``@checked_jit`` decorations, and functions passed to
``jax.jit`` / ``checked_jit`` / ``lax.scan`` / ``shard_map`` calls.

**Concurrency lint.** The threaded runtime's documented discipline
(docs/ANALYSIS.md "Lock discipline"):

1. *single lock order* — at most one lock held at a time unless the
   nested pair is declared in :data:`LOCK_ORDER` (currently empty: the
   runtime deliberately never nests);
2. *no blocking calls under a lock* — no thread ``join``, ``sleep``,
   event/future waits, or filesystem IO while holding a lock. Waiting
   on the HELD Condition itself is exempt (``Condition.wait`` releases
   the lock — the whole point), as is ``os.path.join`` (a string op);
3. *guarded shared writes* — an attribute ever written under a lock
   (outside ``__init__``) is a shared variable and must be written
   under that lock everywhere. Methods named ``*_locked`` are the
   repo's called-with-lock-held convention and count as guarded.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from distributed_eigenspaces_tpu.analysis.contracts import Violation

#: threaded-runtime files the concurrency lint gates (repo-relative)
CONCURRENCY_TARGETS = (
    "distributed_eigenspaces_tpu/runtime/scheduler.py",
    "distributed_eigenspaces_tpu/runtime/supervisor.py",
    "distributed_eigenspaces_tpu/runtime/membership.py",
    "distributed_eigenspaces_tpu/runtime/prewarm.py",
    "distributed_eigenspaces_tpu/serving/registry.py",
    "distributed_eigenspaces_tpu/serving/replication.py",
)

#: jit-path files the host-sync lint gates
HOST_SYNC_TARGETS = (
    "distributed_eigenspaces_tpu/algo/step.py",
    "distributed_eigenspaces_tpu/algo/scan.py",
    "distributed_eigenspaces_tpu/algo/online.py",
    "distributed_eigenspaces_tpu/parallel/feature_sharded.py",
    "distributed_eigenspaces_tpu/parallel/fleet.py",
    "distributed_eigenspaces_tpu/parallel/ring.py",
    "distributed_eigenspaces_tpu/serving/transform.py",
)

#: the documented nesting order: (outer, inner) pairs that MAY nest.
#: Empty = the runtime holds at most one lock at a time — any nesting
#: is a violation until a pair is documented here AND in
#: docs/ANALYSIS.md.
LOCK_ORDER: tuple[tuple[str, str], ...] = ()

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore"}
_BLOCKING_ATTRS = {"join", "sleep", "_sleep", "wait", "wait_for", "result"}
_IO_CHAINS = {
    ("open",),
    ("os", "replace"), ("os", "fsync"), ("os", "rename"),
    ("os", "remove"), ("os", "makedirs"), ("os", "listdir"),
    ("np", "load"), ("np", "save"), ("np", "savez"),
    ("numpy", "load"), ("numpy", "save"), ("numpy", "savez"),
    ("json", "dump"), ("json", "load"),
    ("pickle", "dump"), ("pickle", "load"),
    ("shutil", "rmtree"), ("shutil", "copy"), ("shutil", "move"),
}
_HOST_SYNC_CALLS = {
    ("np", "asarray"), ("np", "array"),
    ("numpy", "asarray"), ("numpy", "array"),
    ("onp", "asarray"), ("onp", "array"),
}


def _chain(node) -> tuple[str, ...]:
    """``a.b.c`` -> ("a", "b", "c"); non-name bases end the chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return tuple(reversed(parts))


def _loc(filename: str, node: ast.AST) -> str:
    return f"{filename}:{getattr(node, 'lineno', '?')}"


# -- concurrency lint --------------------------------------------------------


@dataclass
class _Scope:
    """Lint state for one class (or the module's top level)."""

    name: str
    lock_attrs: set[str] = field(default_factory=set)
    #: attr -> set of lock names it was written under
    written_locked: dict = field(default_factory=dict)
    #: attr -> list of (method, lineno) unlocked writes
    written_unlocked: dict = field(default_factory=dict)


def _lock_name_of(node) -> str | None:
    """The lock token a ``with`` item / call receiver refers to:
    ``self.X`` -> "self.X", bare local ``name`` -> "name"."""
    ch = _chain(node)
    if len(ch) == 2 and ch[0] == "self":
        return f"self.{ch[1]}"
    if len(ch) == 1:
        return ch[0]
    return None


def _is_lock_factory(call) -> bool:
    if not isinstance(call, ast.Call):
        return False
    ch = _chain(call.func)
    return bool(ch) and ch[-1] in _LOCK_FACTORIES and (
        len(ch) == 1 or ch[0] in ("threading", "th")
    )


def lint_concurrency_source(
    src: str,
    filename: str,
    *,
    lock_order: tuple[tuple[str, str], ...] = LOCK_ORDER,
) -> list[Violation]:
    """Lock-discipline lint over one file's source text."""
    tree = ast.parse(src, filename=filename)
    out: list[Violation] = []
    program = os.path.basename(filename)

    def lint_function(fn, scope: _Scope, known_locks: set[str]):
        method = fn.name
        guarded_method = method.endswith("_locked")

        def walk(node, held: tuple[str, ...]):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                # nested def: a new call frame — the lock is NOT held
                # at its definition's execution time
                lint_function(node, scope, known_locks)
                return
            if isinstance(node, ast.With):
                inner = held
                for item in node.items:
                    lk = _lock_name_of(item.context_expr)
                    if lk is not None and lk in known_locks:
                        if inner and lk not in inner and \
                                (inner[-1], lk) not in lock_order:
                            out.append(Violation(
                                program=program,
                                rule="lock-order",
                                message=(
                                    f"acquires {lk} while holding "
                                    f"{inner[-1]} — nesting outside the "
                                    "documented LOCK_ORDER (the runtime "
                                    "holds one lock at a time; document "
                                    "the pair in analysis/ast_lints.py "
                                    "+ docs/ANALYSIS.md or restructure)"
                                ),
                                location=_loc(filename, node),
                            ))
                        inner = inner + (lk,)
                for child in node.body:
                    walk(child, inner)
                return
            if isinstance(node, ast.Call):
                _check_call(node, held)
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    ch = _chain(t)
                    if len(ch) == 2 and ch[0] == "self":
                        attr = ch[1]
                        if held:
                            scope.written_locked.setdefault(
                                attr, set()
                            ).update(held)
                        elif guarded_method:
                            # *_locked convention: caller holds the lock
                            scope.written_locked.setdefault(attr, set())
                        elif method != "__init__":
                            scope.written_unlocked.setdefault(
                                attr, []
                            ).append((method, node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        def _check_call(call, held):
            if not held:
                return
            ch = _chain(call.func)
            if not ch:
                return
            # held-Condition wait is the release-and-wait idiom
            if ch[-1] in ("wait", "wait_for"):
                recv = _lock_name_of(call.func.value) if isinstance(
                    call.func, ast.Attribute
                ) else None
                if recv is not None and recv in held:
                    return
            if ch[:2] == ("os", "path"):  # os.path.join is a string op
                return
            if ch[-1] == "join" and isinstance(call.func, ast.Attribute) \
                    and isinstance(call.func.value, ast.Constant):
                return  # ", ".join(...) string idiom
            blocking = (
                ch[-1] in _BLOCKING_ATTRS
                or ch in _IO_CHAINS
                or (len(ch) == 1 and ch[0] == "open")
                or ch[-1] == "acquire"
            )
            if blocking:
                out.append(Violation(
                    program=program,
                    rule="blocking-under-lock",
                    message=(
                        f"calls {'.'.join(ch)}() while holding "
                        f"{held[-1]} — blocking (join/sleep/wait/IO/"
                        "acquire) under a lock stalls every thread "
                        "contending for it; move the call outside the "
                        "critical section"
                    ),
                    location=_loc(filename, call),
                ))

        for stmt in fn.body:
            walk(stmt, ())

    def lint_class(cls):
        scope = _Scope(name=cls.name)
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                val = node.value
                if _is_lock_factory(val):
                    for t in targets:
                        lk = _lock_name_of(t)
                        if lk is not None:
                            scope.lock_attrs.add(lk)
        known = set(scope.lock_attrs)
        for node in cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                lint_function(node, scope, known)
        for attr, locks in sorted(scope.written_locked.items()):
            for method, lineno in scope.written_unlocked.get(attr, ()):
                lock = sorted(locks)[0] if locks else "its lock"
                out.append(Violation(
                    program=program,
                    rule="unguarded-shared-write",
                    message=(
                        f"{scope.name}.{attr} is written under {lock} "
                        f"elsewhere but written WITHOUT it in "
                        f"{method}() — a shared mutable attribute must "
                        "be touched only under its documented lock "
                        "(or from a *_locked method)"
                    ),
                    location=f"{filename}:{lineno}",
                ))

    # module-level functions get the blocking/nesting checks with any
    # locally-created locks (closure locks like estimators' fold_lock)
    mod_scope = _Scope(name="<module>")
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            lint_class(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            local_locks = {
                lk for n in ast.walk(node)
                if isinstance(n, ast.Assign) and _is_lock_factory(n.value)
                for lk in [_lock_name_of(n.targets[0])] if lk is not None
            }
            lint_function(node, mod_scope, local_locks)
    return out


def lint_concurrency(root: str | None = None) -> list[Violation]:
    """The lock-discipline lint over every runtime target file."""
    root = root or _repo_root()
    out: list[Violation] = []
    for rel in CONCURRENCY_TARGETS:
        path = os.path.join(root, rel)
        with open(path, encoding="utf-8") as f:
            out += lint_concurrency_source(f.read(), rel)
    return out


# -- host-sync lint ----------------------------------------------------------


def _traced_functions(tree) -> list[ast.FunctionDef]:
    """Functions that are jit-traced: decorated with jit/checked_jit,
    or passed (by name) to jit/checked_jit/lax.scan/shard_map calls."""
    by_name: dict[str, list[ast.FunctionDef]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            by_name.setdefault(node.name, []).append(node)
    traced: list[ast.FunctionDef] = []
    seen: set[int] = set()

    def mark(fn):
        if id(fn) not in seen:
            seen.add(id(fn))
            traced.append(fn)

    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                ch = _chain(base)
                if ch and ch[-1] in ("jit", "checked_jit"):
                    mark(node)
                if ch and ch[-1] == "partial" and isinstance(dec, ast.Call):
                    for a in dec.args:
                        ach = _chain(a)
                        if ach and ach[-1] in ("jit", "checked_jit"):
                            mark(node)
        if isinstance(node, ast.Call):
            ch = _chain(node.func)
            if not ch:
                continue
            if ch[-1] in ("jit", "checked_jit", "scan", "shard_map"):
                for a in node.args[:1]:
                    if isinstance(a, ast.Name):
                        for fn in by_name.get(a.id, ()):
                            mark(fn)
    return traced


def lint_host_sync_source(src: str, filename: str) -> list[Violation]:
    """Host-sync lint over one file's source text."""
    tree = ast.parse(src, filename=filename)
    out: list[Violation] = []
    program = os.path.basename(filename)
    for fn in _traced_functions(tree):
        params = {
            a.arg for a in (
                fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs
            )
        } - {"self"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                ch = _chain(node.func)
                if ch and ch[-1] == "item" and isinstance(
                    node.func, ast.Attribute
                ):
                    out.append(Violation(
                        program=program,
                        rule="host-sync",
                        message=(
                            f".item() inside jit-traced {fn.name}() "
                            "forces a device->host sync mid-program; "
                            "keep the value on device or move the read "
                            "outside the jitted path"
                        ),
                        location=_loc(filename, node),
                    ))
                elif ch in _HOST_SYNC_CALLS:
                    out.append(Violation(
                        program=program,
                        rule="host-sync",
                        message=(
                            f"{'.'.join(ch)}() inside jit-traced "
                            f"{fn.name}() materializes a traced value "
                            "on host (sync + constant-folds the array "
                            "into the program); use jnp instead"
                        ),
                        location=_loc(filename, node),
                    ))
                elif ch in (("float",), ("int",), ("bool",)) and \
                        node.args and not isinstance(
                            node.args[0], ast.Constant
                        ):
                    ach = _chain(node.args[0])
                    if ach and ach[0] in params:
                        out.append(Violation(
                            program=program,
                            rule="host-sync",
                            message=(
                                f"{ch[0]}() on traced argument "
                                f"{'.'.join(ach)!r} inside jit-traced "
                                f"{fn.name}() forces concretization; "
                                "use jnp casts on device"
                            ),
                            location=_loc(filename, node),
                        ))
            elif isinstance(node, ast.If):
                tch = _chain(node.test)
                if tch and len(tch) == 1 and tch[0] in params:
                    out.append(Violation(
                        program=program,
                        rule="traced-branch",
                        message=(
                            f"Python `if {tch[0]}:` on a traced "
                            f"argument of jit-traced {fn.name}() — a "
                            "data-dependent Python branch fails to "
                            "trace (or silently specializes); use "
                            "lax.cond / jnp.where"
                        ),
                        location=_loc(filename, node),
                    ))
    return out


def lint_host_sync(root: str | None = None) -> list[Violation]:
    """The host-sync lint over every jit-path target file."""
    root = root or _repo_root()
    out: list[Violation] = []
    for rel in HOST_SYNC_TARGETS:
        path = os.path.join(root, rel)
        with open(path, encoding="utf-8") as f:
            out += lint_host_sync_source(f.read(), rel)
    return out


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
