"""Offline planner: a declared workload in, an auditable ``plan-v1``
artifact out — the cost model closing the configuration loop (ISSUE 19).

Every scale knob the system has grown (``merge_topology``,
``merge_interval``, ``pipeline_merge``, serve bucket sizes,
``serve_continuous``, replica count) is hand-picked even though the
static cost model (:mod:`.costmodel`) already prices topologies and the
committed ``BENCH_*_SMOKE_CPU.json`` records already measure the serve
path. The planner connects them:

- **Enumerate** candidate configs over the existing elastic surfaces
  only — merge-tree fan-in splits of the declared worker mesh, the
  (``pipeline_merge`` x ``merge_interval``) arms the measured
  ``EXP_PIPELINE_CPU.json`` grid admits, serve bucket sizes, continuous
  vs deadline batching, replica counts up to the declared fleet.
- **Price** each candidate with the closed-form per-tier wire model at
  the declared link speeds (the same ring formulas
  :func:`.costmodel.projections` commits) plus serve/compile terms
  calibrated from the committed smoke records (FLOP-scaled from each
  record's own shape, so the calibration is exact at the record and a
  declared extrapolation elsewhere).
- **Refuse loudly** when the spec is infeasible: no tier split divides
  the worker mesh over the declared fleet (``PlanInfeasible``), or
  every candidate's predicted p99 lands over the declared SLO / a tier
  budget over the round deadline (the rejection histogram rides the
  error).

The chosen config + predicted budgets are emitted as a deterministic
JSON artifact (no timestamps — regeneration on clean HEAD is a no-op
diff) that ``cli.py --plan`` consumes and ``scripts/analyze.py --plan``
diff-gates against the committed ``ANALYSIS_PLAN.json`` (rule
``plan-drift``, like ``ANALYSIS_COSTS.json``). :func:`self_check`
re-verifies any plan against its own declared budgets (rule
``plan-infeasible`` — the seeded ``plan_infeasible_accepted`` mutation's
hook), and :func:`drift_check` compares the plan's model-anchored
predictions against the CURRENT measured records: warn at
:data:`DRIFT_WARN_RATIO` x, fail at :data:`DRIFT_FAIL_RATIO` x.
"""

from __future__ import annotations

import hashlib
import json
import os

from distributed_eigenspaces_tpu.analysis import costmodel

PLAN_SCHEMA = "plan-v1"
PLAN_NAME = "ANALYSIS_PLAN.json"

#: model-vs-measured drift gate: a calibrated prediction more than
#: WARN x off the current committed record warns in CI; FAIL x fails.
DRIFT_WARN_RATIO = 2.0
DRIFT_FAIL_RATIO = 5.0

#: the serve elastic surfaces the planner enumerates (the autoscaler
#: acts on the same set — one knob vocabulary for both halves)
_BUCKET_CANDIDATES = (4, 8, 16, 32)
_FLUSH_S_CANDIDATES = (0.02, 0.05)

#: workload spec: a CLOSED field set, like scenario specs — an unknown
#: field is a spec bug, not a default silently applied
_WORKLOAD_FIELDS = {
    "name", "d", "k", "m", "n", "qps", "fleet", "rows_per_query",
    "slo_p99_ms", "round_deadline_ms", "ici_gb_per_sec",
    "dcn_gb_per_sec",
}
_REQUIRED_FIELDS = {"d", "k", "m", "n", "qps", "slo_p99_ms"}

#: the audit-shape workload CI gates (scripts/analyze.py --plan): the
#: d=32768 pod the cost model's committed projections already price
DEFAULT_WORKLOAD = {
    "name": "audit_pod",
    "d": 32768, "k": 8, "m": 64, "n": 128,
    # 250 qps/pod: what the CPU-calibrated serve ceiling can clear at
    # d=32768 under the 500 ms SLO — a TPU re-calibration (ROADMAP
    # hardware-truth sweep) raises the declarable rate, not the model
    "qps": 250.0, "fleet": 8, "rows_per_query": 8,
    "slo_p99_ms": 500.0, "round_deadline_ms": 250.0,
    "ici_gb_per_sec": costmodel.ICI_GB_PER_SEC,
    "dcn_gb_per_sec": costmodel.DCN_GB_PER_SEC,
}


class PlanInfeasible(ValueError):
    """The declared workload admits NO feasible candidate — refused
    loudly with the per-reason rejection histogram, never silently
    planned anyway."""


def plan_file_path() -> str:
    """The committed artifact lives at the repo root, next to
    ``ANALYSIS_COSTS.json``."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.join(
        os.path.dirname(os.path.dirname(here)), PLAN_NAME
    )


def load_plan(path: str | None = None) -> dict | None:
    path = path or plan_file_path()
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def validate_workload(spec: dict) -> dict:
    """Loud validation of a declared workload: closed field set,
    required fields present, values positive and mutually coherent."""
    if not isinstance(spec, dict):
        raise ValueError(
            f"workload spec must be a dict, got {type(spec).__name__}"
        )
    extra = set(spec) - _WORKLOAD_FIELDS
    if extra:
        raise ValueError(
            f"unknown workload field(s) {sorted(extra)} — known fields: "
            f"{sorted(_WORKLOAD_FIELDS)}"
        )
    missing = _REQUIRED_FIELDS - set(spec)
    if missing:
        raise ValueError(
            f"workload spec missing required field(s) {sorted(missing)}"
        )
    out = dict(DEFAULT_WORKLOAD)
    out.update(spec)
    for field in ("d", "k", "m", "n", "fleet", "rows_per_query"):
        v = out[field]
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            raise ValueError(
                f"workload {field} must be an int >= 1, got {v!r}"
            )
    for field in (
        "qps", "slo_p99_ms", "round_deadline_ms",
        "ici_gb_per_sec", "dcn_gb_per_sec",
    ):
        v = out[field]
        if not isinstance(v, (int, float)) or isinstance(
            v, bool
        ) or v <= 0:
            raise ValueError(
                f"workload {field} must be a positive number, got {v!r}"
            )
    if out["k"] > out["d"]:
        raise ValueError(
            f"workload needs k <= d, got k={out['k']}, d={out['d']}"
        )
    if not isinstance(out["name"], str) or not out["name"]:
        raise ValueError(
            f"workload name must be a non-empty string, got "
            f"{out['name']!r}"
        )
    return out


# -- calibration: committed smoke records as model anchors -------------------


#: committed record -> the calibrated terms it anchors. Every term
#: carries its source record + field so the artifact is auditable.
_CALIBRATION_SOURCES = {
    "BENCH_WIRESPEED_SMOKE_CPU.json": (
        "serve admit p99 (continuous) + fused kernel ms at the "
        "wirespeed shape"
    ),
    "BENCH_SERVE_SMOKE_CPU.json": (
        "deadline-batched serve p99 at the serve smoke shape"
    ),
    "BENCH_COLDSTART_SMOKE_CPU.json": (
        "warm-vs-cold first-serve compile amortization"
    ),
    "EXP_PIPELINE_CPU.json": (
        "(pipeline_merge x merge_interval) measured speedups + the "
        "0.2 deg accuracy gate per arm"
    ),
}


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def load_calibration(root: str | None = None) -> dict:
    """The calibrated serve/compile/schedule terms, read from the
    committed smoke records. A missing record drops its terms (the
    planner falls back to the closed-form-only model and says so in
    the artifact) — never a crash, never a silent default."""
    root = root or _repo_root()
    calib: dict = {"sources": {}, "terms": {}}

    def rec(name):
        path = os.path.join(root, name)
        if not os.path.exists(path):
            return None
        with open(path, encoding="utf-8") as f:
            loaded = json.load(f)
        calib["sources"][name] = _CALIBRATION_SOURCES.get(name, "")
        return loaded

    wire = rec("BENCH_WIRESPEED_SMOKE_CPU.json")
    if wire is not None:
        shape = wire.get("wirespeed_shape", {})
        rows = shape.get("rows_per_query", 8) * shape.get("bucket", 8)
        calib["terms"]["serve_admit_p99_ms"] = {
            "value": wire.get("value"),
            "source": "BENCH_WIRESPEED_SMOKE_CPU.json:value",
        }
        kern = (wire.get("kernel_ms") or {}).get("float32")
        if kern is not None:
            calib["terms"]["serve_kernel_ms"] = {
                "value": kern,
                "at_rows_dk": [rows, shape.get("dim", 64),
                               shape.get("k", 8)],
                "source": "BENCH_WIRESPEED_SMOKE_CPU.json:kernel_ms",
            }
    serve = rec("BENCH_SERVE_SMOKE_CPU.json")
    if serve is not None:
        calib["terms"]["serve_deadline_p99_ms"] = {
            "value": round(
                float(serve.get("p99_latency_s", 0.0)) * 1e3, 3
            ),
            "at_flush_ms": round(
                float(serve.get("serve_flush_s", 0.05)) * 1e3, 3
            ),
            "source": "BENCH_SERVE_SMOKE_CPU.json:p99_latency_s",
        }
    cold = rec("BENCH_COLDSTART_SMOKE_CPU.json")
    if cold is not None:
        calib["terms"]["warm_first_serve_ms"] = {
            "value": round(
                float(cold.get("warm_first_serve_s", 0.0)) * 1e3, 3
            ),
            "source": "BENCH_COLDSTART_SMOKE_CPU.json:warm_first_serve_s",
        }
    grid = rec("EXP_PIPELINE_CPU.json")
    if grid is not None:
        arms = {}
        for arm_name, row in (grid.get("grid") or {}).items():
            arms[arm_name] = {
                "speedup": row.get("speedup_vs_baseline"),
                "gate_0p2deg_ok": bool(row.get("gate_0p2deg_ok")),
                "warm_ms_per_step": row.get("warm_ms_per_step"),
            }
        calib["terms"]["fit_schedule_arms"] = {
            "value": arms,
            "source": "EXP_PIPELINE_CPU.json:grid",
        }
    return calib


def _schedule_arms(calib: dict) -> list[tuple[bool, int, float]]:
    """The (pipeline_merge, merge_interval, measured_speedup) arms the
    planner may choose from: only arms the committed grid MEASURED and
    whose 0.2 deg accuracy gate passed. Without the grid record only
    the identity arm (off, 1, 1.0) is admissible — an unmeasured
    schedule restructure is not a plannable win."""
    arms = [(False, 1, 1.0)]
    term = calib.get("terms", {}).get("fit_schedule_arms")
    if term is None:
        return arms
    for name, row in term["value"].items():
        if not row.get("gate_0p2deg_ok") or row.get("speedup") is None:
            continue
        try:
            pipe_tok, s_tok = name.split(",")
            pipe = pipe_tok.split("=")[1] == "on"
            s = int(s_tok.split("=")[1])
        except (IndexError, ValueError):
            continue
        if (pipe, s) != (False, 1):
            arms.append((pipe, s, float(row["speedup"])))
    return arms


# -- candidate enumeration ----------------------------------------------------


def _tier_splits(m: int, fleet: int) -> list[tuple | None]:
    """Merge topologies whose fan-in product divides the worker mesh
    over the declared fleet: flat (None) always, plus every two-tier
    ("chip", f_chip) / ("host", f_host) split with f_chip * f_host ==
    m and f_host <= fleet (the root tier cannot fan wider than the
    hosts it crosses). Workers must pack evenly onto hosts — a mesh no
    split divides is the caller's PlanInfeasible."""
    splits: list[tuple | None] = [None]
    if m % fleet != 0:
        return splits
    for f_host in range(2, min(m, fleet) + 1):
        if m % f_host:
            continue
        f_chip = m // f_host
        if f_chip < 2:
            continue
        splits.append((("chip", f_chip), ("host", f_host)))
    return splits


#: the wire policies the planner enumerates for tiered merges
#: (ISSUE 20): uncompressed, and the host (DCN) tier narrowed to each
#: codec. Chip-tier compression is not enumerated — ICI is never the
#: binding constraint in the priced workloads, so it would only grow
#: the candidate set without changing any choice.
_WIRE_POLICY_CANDIDATES: tuple[dict | None, ...] = (
    None, {"host": "bf16"}, {"host": "int8"},
)


def enumerate_candidates(spec: dict, calib: dict) -> list[dict]:
    """The candidate configs, elastic surfaces only: tier splits x
    host-tier wire dtype x measured schedule arms x serve
    bucket/flush/continuous x replica counts (powers of two up to the
    fleet)."""
    replicas = []
    r = 1
    while r <= spec["fleet"]:
        replicas.append(r)
        r *= 2
    cands = []
    for topo in _tier_splits(spec["m"], spec["fleet"]):
        # flat merges have no tiers to compress (config refuses the
        # combination for the same reason)
        wire_opts = _WIRE_POLICY_CANDIDATES if topo else (None,)
        for wire in wire_opts:
            for pipe, interval, speedup in _schedule_arms(calib):
                if topo is not None and pipe:
                    continue  # merge_topology rejects pipeline_merge
                for bucket in _BUCKET_CANDIDATES:
                    for flush_s in _FLUSH_S_CANDIDATES:
                        for cont in (False, True):
                            for n_rep in replicas:
                                cands.append({
                                    "merge_topology": topo,
                                    "merge_wire_dtype": wire,
                                    "pipeline_merge": pipe,
                                    "merge_interval": interval,
                                    "schedule_speedup": speedup,
                                    "serve_bucket_size": bucket,
                                    "serve_flush_s": flush_s,
                                    "serve_continuous": cont,
                                    "replicas": n_rep,
                                })
    return cands


# -- pricing ------------------------------------------------------------------


def _fit_tiers(cand: dict, spec: dict) -> dict:
    """Per-tier wire bytes + modeled ms per merge round at the
    DECLARED link speeds — the exact ring formulas
    :func:`.costmodel.projections` commits, evaluated on this
    candidate's topology. Flat merges price the m-wide factor gather
    on one tier, over DCN whenever the mesh spans more than one
    host."""
    d, k, m = spec["d"], spec["k"], spec["m"]
    itemsize = costmodel.BUDGET_ITEMSIZE
    ici, dcn = spec["ici_gb_per_sec"], spec["dcn_gb_per_sec"]
    tiers = {}
    if cand["merge_topology"] is None:
        wire = int(costmodel._ring(m) * m * d * k * itemsize)
        # a flat merge's gather spans the whole mesh: single-host
        # fleets ride ICI, anything wider crosses DCN
        gbps = ici if spec["fleet"] == 1 else dcn
        tiers["workers"] = {
            "fan_in": m,
            "wire_bytes_per_round": wire,
            "assumed_gb_per_sec": gbps,
            "modeled_ms_per_round": round(wire / (gbps * 1e9) * 1e3, 4),
        }
    else:
        from distributed_eigenspaces_tpu.parallel.wire import (
            WIRE_ITEMSIZE,
        )

        policy = cand.get("merge_wire_dtype") or {}
        for name, fan in cand["merge_topology"]:
            # the two data movers ship at the tier's declared codec
            # width; the Gram psum stays f32 (accumulation is never
            # compressed) — the same split model_costs commits
            dtype = policy.get(name, "fp32")
            ring = costmodel._ring(fan)
            wire = int(
                ring * 2 * d * k * WIRE_ITEMSIZE[dtype]
                + ring * 2 * (fan * k) ** 2 * itemsize
            )
            if dtype == "int8":
                wire += int(ring * (fan + 1) * k * itemsize)
            gbps = ici if name == "chip" else dcn
            tiers[name] = {
                "fan_in": fan,
                "wire_bytes_per_round": wire,
                "assumed_gb_per_sec": gbps,
                "modeled_ms_per_round": round(
                    wire / (gbps * 1e9) * 1e3, 4
                ),
            }
            if dtype != "fp32":
                tiers[name]["wire_dtype"] = dtype
    return tiers


def _serve_terms(cand: dict, spec: dict, calib: dict) -> dict:
    """Predicted serve p99 decomposed the way the telemetry decomposes
    measured p99 (queue wait + compute), from the calibrated terms:
    admit/fill wait from the batching mode, kernel ms FLOP-scaled from
    the wirespeed record's shape. CPU-rig calibrated — a ceiling, and
    says so in the artifact."""
    terms = calib.get("terms", {})
    qps_per_replica = spec["qps"] / cand["replicas"]
    rows_batch = cand["serve_bucket_size"] * spec["rows_per_query"]
    if cand["serve_continuous"]:
        admit = terms.get("serve_admit_p99_ms")
        wait_ms = float(admit["value"]) if admit else 0.1
    else:
        # deadline batching: wait for the bucket to fill, capped by the
        # flush deadline — at low per-replica qps the deadline IS the
        # p99 wait, which is what the serve smoke record measures
        fill_ms = (
            1e3 * (cand["serve_bucket_size"] - 1) / qps_per_replica
            if qps_per_replica > 0 else float("inf")
        )
        wait_ms = min(cand["serve_flush_s"] * 1e3, fill_ms)
    kern = terms.get("serve_kernel_ms")
    if kern:
        rows0, d0, k0 = kern["at_rows_dk"]
        compute_ms = float(kern["value"]) * (
            (rows_batch * spec["d"] * spec["k"]) / (rows0 * d0 * k0)
        )
    else:
        compute_ms = 0.5
    overhead = terms.get("warm_first_serve_ms")
    # warm-path dispatch overhead amortizes over the bucket; the cold
    # first-serve compile is a one-off the plan does not budget per query
    overhead_ms = (
        float(overhead["value"]) / 100.0 if overhead else 0.5
    )
    p99 = round(wait_ms + compute_ms + overhead_ms, 3)
    util = (
        compute_ms * qps_per_replica
        / max(cand["serve_bucket_size"], 1) / 1e3
    )
    return {
        "queue_wait_p99_ms": round(wait_ms, 3),
        "batch_compute_ms": round(compute_ms, 3),
        "dispatch_overhead_ms": round(overhead_ms, 3),
        "predicted_p99_ms": p99,
        "replica_utilization": round(util, 4),
        "qps_per_replica": round(qps_per_replica, 1),
    }


def price_candidate(cand: dict, spec: dict, calib: dict) -> dict:
    """One candidate's predicted budgets + scalar cost. The score is
    explicit in the artifact: amortized fit wire ms per step (merge
    every ``merge_interval`` steps, divided by the measured schedule
    speedup) + 0.01 x predicted serve p99 + 0.1 x replicas (capacity
    is not free)."""
    tiers = _fit_tiers(cand, spec)
    round_ms = sum(t["modeled_ms_per_round"] for t in tiers.values())
    fit_ms_per_step = round(
        round_ms / cand["merge_interval"] / cand["schedule_speedup"], 4
    )
    serve = _serve_terms(cand, spec, calib)
    score = round(
        fit_ms_per_step
        + 0.01 * serve["predicted_p99_ms"]
        + 0.1 * cand["replicas"],
        4,
    )
    return {
        "fit_tiers": tiers,
        "fit_round_ms": round(round_ms, 4),
        "fit_ms_per_step": fit_ms_per_step,
        "serve": serve,
        "score": score,
    }


def _reject_reason(priced: dict, spec: dict) -> str | None:
    """Why a priced candidate is infeasible, or None. The same checks
    :func:`self_check` re-applies to an emitted plan."""
    for name, tier in priced["fit_tiers"].items():
        if tier["modeled_ms_per_round"] > spec["round_deadline_ms"]:
            return f"tier_over_deadline:{name}"
    if priced["serve"]["predicted_p99_ms"] > spec["slo_p99_ms"]:
        return "p99_over_slo"
    if priced["serve"]["replica_utilization"] >= 1.0:
        return "replica_saturated"
    return None


# -- the plan -----------------------------------------------------------------


def make_plan(
    spec: dict | None = None, calibration: dict | None = None
) -> dict:
    """Enumerate, price, choose; emit the auditable artifact. Raises
    :class:`PlanInfeasible` (with the rejection histogram) when no
    candidate survives, and re-runs :func:`self_check` on the result
    so an emitted plan can never fail its own audit."""
    spec = validate_workload(spec or DEFAULT_WORKLOAD)
    calib = calibration if calibration is not None else load_calibration()
    if spec["m"] % spec["fleet"] != 0:
        raise PlanInfeasible(
            f"no topology divides the mesh: m={spec['m']} workers do "
            f"not pack onto fleet={spec['fleet']} hosts (m % fleet != "
            "0) — declare a fleet that divides the worker mesh"
        )
    candidates = enumerate_candidates(spec, calib)
    rejected: dict[str, int] = {}
    best = None
    for cand in candidates:
        priced = price_candidate(cand, spec, calib)
        reason = _reject_reason(priced, spec)
        if reason is not None:
            rejected[reason] = rejected.get(reason, 0) + 1
            continue
        key = (
            priced["score"],
            # deterministic tie-break: prefer fewer replicas, smaller
            # buckets, then the spelled-out config
            cand["replicas"],
            cand["serve_bucket_size"],
            json.dumps(cand, sort_keys=True, default=list),
        )
        if best is None or key < best[0]:
            best = (key, cand, priced)
    if best is None:
        raise PlanInfeasible(
            f"workload {spec['name']!r} admits no feasible candidate "
            f"out of {len(candidates)}: rejections "
            f"{json.dumps(dict(sorted(rejected.items())))} — relax the "
            f"SLO ({spec['slo_p99_ms']} ms), the round deadline "
            f"({spec['round_deadline_ms']} ms), or grow the fleet"
        )
    _, cand, priced = best
    overrides = {
        "merge_topology": (
            [list(t) for t in cand["merge_topology"]]
            if cand["merge_topology"] else None
        ),
        "merge_wire_dtype": cand["merge_wire_dtype"],
        "pipeline_merge": cand["pipeline_merge"],
        "merge_interval": cand["merge_interval"],
        "serve_bucket_size": cand["serve_bucket_size"],
        "serve_flush_s": cand["serve_flush_s"],
        "serve_continuous": cand["serve_continuous"],
        "replicas": cand["replicas"],
    }
    plan = {
        "schema": PLAN_SCHEMA,
        "workload": spec,
        "calibration": calib,
        "candidates_considered": len(candidates),
        "rejected": dict(sorted(rejected.items())),
        "chosen": {
            "config_overrides": overrides,
            "predicted": priced,
        },
        "objective": (
            "min fit_ms_per_step + 0.01*predicted_p99_ms + "
            "0.1*replicas over feasible candidates (tier ms <= "
            "round_deadline_ms, p99 <= slo_p99_ms, utilization < 1)"
        ),
        "drift_anchors": _drift_anchors(calib),
    }
    plan["plan_id"] = "plan-" + hashlib.sha256(
        json.dumps(
            {"workload": spec, "chosen": plan["chosen"]},
            sort_keys=True,
        ).encode()
    ).hexdigest()[:12]
    viols = self_check(plan)
    if viols:
        raise PlanInfeasible(
            "emitted plan failed its own self-check: "
            + "; ".join(v.format() for v in viols)
        )
    return plan


def _drift_anchors(calib: dict) -> dict:
    """The plan's model-anchored predictions AT THE RECORD SHAPES —
    ratio 1.0 against the records the calibration read, by
    construction. :func:`drift_check` later compares these stored
    values against the records CURRENT at check time: re-recording a
    bench 2x slower (or changing the model) moves the ratio, and CI
    warns/fails — the model-vs-measured drift gate."""
    anchors = {}
    for name in (
        "serve_admit_p99_ms", "serve_kernel_ms",
        "serve_deadline_p99_ms", "warm_first_serve_ms",
    ):
        term = calib.get("terms", {}).get(name)
        if term is not None and term.get("value") is not None:
            anchors[name] = {
                "predicted": term["value"], "source": term["source"],
            }
    return anchors


def self_check(plan: dict) -> list:
    """The planner's own audit, applied to any ``plan-v1`` dict (ours
    or a hand-edited one): predicted tier budgets within the declared
    round deadline, predicted p99 within the declared SLO, overrides
    buildable as a PCAConfig. Every breach is one ``plan-infeasible``
    violation — the rule the seeded ``plan_infeasible_accepted``
    mutation must see caught."""
    from distributed_eigenspaces_tpu.analysis.contracts import (
        Violation,
    )

    viols: list = []

    def refuse(message: str, location: str = "") -> None:
        viols.append(Violation(
            program="planner", rule="plan-infeasible",
            message=message, location=location,
        ))

    if plan.get("schema") != PLAN_SCHEMA:
        refuse(
            f"unknown plan schema {plan.get('schema')!r} (expected "
            f"{PLAN_SCHEMA!r})"
        )
        return viols
    spec = plan.get("workload", {})
    chosen = plan.get("chosen", {})
    predicted = chosen.get("predicted", {})
    deadline = spec.get("round_deadline_ms")
    for name, tier in (predicted.get("fit_tiers") or {}).items():
        ms = tier.get("modeled_ms_per_round")
        if deadline is not None and ms is not None and ms > deadline:
            refuse(
                f"predicted {name}-tier budget {ms} ms/round exceeds "
                f"the declared round deadline {deadline} ms — the "
                "plan accepts a merge that cannot close its rounds",
                location=f"chosen.predicted.fit_tiers.{name}",
            )
    p99 = (predicted.get("serve") or {}).get("predicted_p99_ms")
    slo = spec.get("slo_p99_ms")
    if p99 is not None and slo is not None and p99 > slo:
        refuse(
            f"predicted serve p99 {p99} ms exceeds the declared SLO "
            f"{slo} ms — the plan accepts a config that burns its "
            "error budget by construction",
            location="chosen.predicted.serve.predicted_p99_ms",
        )
    overrides = chosen.get("config_overrides")
    if overrides is not None:
        from distributed_eigenspaces_tpu.config import PCAConfig

        try:
            kw = dict(overrides)
            topo = kw.get("merge_topology")
            if topo is not None:
                kw["merge_topology"] = tuple(
                    tuple(t) for t in topo
                )
            PCAConfig(
                dim=spec.get("d", 8), k=spec.get("k", 2),
                num_workers=spec.get("m", 1),
                rows_per_worker=spec.get("n", 1), **kw,
            )
        except (TypeError, ValueError) as e:
            refuse(
                f"chosen config overrides do not build a valid "
                f"PCAConfig: {e}",
                location="chosen.config_overrides",
            )
    return viols


# -- CI gates: artifact diff + model-vs-measured drift ------------------------


def check_plan(current: dict, committed: dict | None) -> list:
    """Diff-gate, exactly like :func:`.costmodel.check_snapshot`:
    regenerated plan vs the committed artifact, every mismatch one
    ``plan-drift`` violation naming the field and both values.
    Intentional changes re-commit via ``scripts/analyze.py
    --write-plan``."""
    from distributed_eigenspaces_tpu.analysis.contracts import (
        Violation,
    )

    viols: list = []

    def drift(message: str, location: str = "") -> None:
        viols.append(Violation(
            program="plan-snapshot", rule="plan-drift",
            message=message, location=location,
        ))

    if committed is None:
        drift(
            f"no committed {PLAN_NAME} found — generate it with "
            "scripts/analyze.py --plan --write-plan and commit the "
            "file"
        )
        return viols
    for key in (
        "schema", "workload", "calibration", "candidates_considered",
        "rejected", "chosen", "objective", "drift_anchors", "plan_id",
    ):
        if current.get(key) != committed.get(key):
            drift(
                f"{key} drifted: committed {committed.get(key)!r} != "
                f"regenerated {current.get(key)!r}",
                location=key,
            )
    return viols


def drift_check(
    plan: dict | None = None, root: str | None = None
) -> list[dict]:
    """Model-vs-measured: the plan's stored drift anchors against the
    records CURRENTLY committed. One row per anchor with the ratio
    (symmetric, max(pred/meas, meas/pred)) and a status: ``ok`` below
    :data:`DRIFT_WARN_RATIO` x, ``warn`` below
    :data:`DRIFT_FAIL_RATIO` x, ``fail`` at or above — the thresholds
    CI applies. A missing record or anchor is a loud ``missing``
    row, not a silent pass."""
    plan = plan or load_plan()
    if plan is None:
        return [{
            "anchor": PLAN_NAME, "status": "missing",
            "detail": "no committed plan artifact to check",
        }]
    calib_now = load_calibration(root)
    rows = []
    for name, anchor in (plan.get("drift_anchors") or {}).items():
        pred = anchor.get("predicted")
        term = calib_now.get("terms", {}).get(name)
        meas = term.get("value") if term else None
        if pred is None or meas is None:
            rows.append({
                "anchor": name, "status": "missing",
                "predicted": pred, "measured": meas,
                "detail": anchor.get("source", ""),
            })
            continue
        pred_f, meas_f = float(pred), float(meas)
        if pred_f <= 0 or meas_f <= 0:
            ratio = float("inf") if pred_f != meas_f else 1.0
        else:
            ratio = max(pred_f / meas_f, meas_f / pred_f)
        status = (
            "ok" if ratio < DRIFT_WARN_RATIO
            else "warn" if ratio < DRIFT_FAIL_RATIO
            else "fail"
        )
        rows.append({
            "anchor": name, "status": status,
            "predicted": pred, "measured": meas,
            "ratio": round(ratio, 3),
            "source": anchor.get("source", ""),
        })
    return rows
