"""Mutation self-tests: the gate that checks the checker.

Each mutation seeds ONE violation class — a reintroduced dense
``psum``, a materialized ``d x d`` temp, a baked-in array constant, a
blocking call under a lock, … — and requires the matching checker to
flag it with the expected rule. A static-analysis stage that can only
pass is worthless; CI stage "analyze" runs ``--mutation-check`` so a
refactor that silently blinds a pass fails the build.

Compiled mutants are built in memory (tiny shapes, ~1 s total); AST
mutants are source-text fixtures fed to the ``lint_*_source`` entry
points. Nothing here touches the tree.
"""

from __future__ import annotations

from typing import Callable

from distributed_eigenspaces_tpu.analysis import ast_lints, contracts

_D = 64


def _mutant_dense_collective() -> list[contracts.Violation]:
    """The design this framework replaced: a shard_map round that
    psums the dense d x d mean projector across the mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.parallel.mesh import (
        make_mesh,
        shard_map,
    )

    mesh = make_mesh(num_workers=8)

    def dense_round(x):  # (m_local, n, d) -> psum of d x d projector
        g = jnp.einsum("mnd,mne->de", x, x)
        return jax.lax.psum(g, "workers")

    f = jax.jit(shard_map(
        dense_round, mesh=mesh, in_specs=P("workers"), out_specs=P(),
        check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((8, 8, _D), jnp.float32)
    ).compile().as_text()
    contract = contracts.CONTRACTS["scan_fit"]
    params = contracts.ProgramParams(d=_D, k=2, m=8, n=8)
    viols, _ = contracts.check_collectives(
        contract, params, hlo, program="mutant_dense_collective"
    )
    return viols


def _mutant_tree_dense_collective() -> list[contracts.Violation]:
    """The tree-merge shortcut the tier contract forbids: a tiered-mesh
    round that psums the dense d x d projector across a tier axis
    instead of the sharded (f*k)^2 Gram. all-reduce itself is in the
    tree contract's allowed set — the PAYLOAD bound is what must
    catch this."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.parallel.mesh import shard_map
    from distributed_eigenspaces_tpu.parallel.topology import (
        MergeTopology,
        make_tiered_mesh,
    )

    topo = MergeTopology((("chip", 2), ("host", 2)))
    mesh = make_tiered_mesh(topo)

    def dense_tier_round(v):  # (d, k) -> psum of d x d across the tier
        p = v @ v.T
        return jax.lax.psum(p, "chip")

    f = jax.jit(shard_map(
        dense_tier_round, mesh=mesh, in_specs=P(), out_specs=P(),
        check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((_D, 2), jnp.float32)
    ).compile().as_text()
    contract = contracts.CONTRACTS["tree_merge"]
    params = contracts.ProgramParams(
        d=_D, k=2, m=4, n=8, tier_fan_ins=topo.fan_ins
    )
    viols, _ = contracts.check_collectives(
        contract, params, hlo, program="mutant_tree_dense_collective"
    )
    return viols


def _mutant_dense_temp() -> list[contracts.Violation]:
    """A factor-only program that materializes the d x d Gram."""
    import jax
    import jax.numpy as jnp

    def gram(x):  # (rows, d) -> (d, d): exactly what serve must not do
        return x.T @ x

    jitted = jax.jit(gram)
    arg = jax.ShapeDtypeStruct((16, _D), jnp.float32)
    contract = contracts.CONTRACTS["serve_transform"]
    params = contracts.ProgramParams(d=_D, k=2, rows=16)
    viols, _ = contracts.check_memory(
        contract, params,
        program="mutant_dense_temp",
        hlo_text=jitted.lower(arg).compile().as_text(),
        closed_jaxpr=jitted.trace(arg).jaxpr,
    )
    return viols


def _mutant_baked_constant() -> list[contracts.Violation]:
    """A serving kernel that closes over the basis instead of taking
    it as an operand."""
    import jax
    import jax.numpy as jnp

    v_baked = jnp.ones((_D, 2), jnp.float32)

    def project(x):
        return x @ v_baked

    jitted = jax.jit(project)
    arg = jax.ShapeDtypeStruct((16, _D), jnp.float32)
    contract = contracts.CONTRACTS["serve_transform"]
    params = contracts.ProgramParams(d=_D, k=2, rows=16)
    viols, _ = contracts.check_consts(
        contract, params, jitted.trace(arg).jaxpr,
        program="mutant_baked_constant",
    )
    return viols


def _mutant_replicated_dk() -> list[contracts.Violation]:
    """The distributed-solve regression the sharding contracts exist
    for (ISSUE 13): a feature-sharded step whose (d, q) basis comes
    back REPLICATED (the partitioner quietly all-gathers it) despite
    the contract declaring it sharded over 'features'. The
    silent-replication rule must name program + buffer shape + the
    offending HLO location."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_eigenspaces_tpu.analysis import (
        shardings as sh_mod,
    )
    from distributed_eigenspaces_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(num_workers=4, num_feature_shards=2)
    d, q = 2 * _D, 2
    fn = jax.jit(
        lambda v: 2.0 * v,
        in_shardings=NamedSharding(mesh, P("features", None)),
        out_shardings=NamedSharding(mesh, P()),  # the regression
    )
    arg = jax.ShapeDtypeStruct((d, q), jnp.float32)
    compiled = fn.lower(arg).compile()
    contract = contracts.CONTRACTS["feature_sharded"]
    params = contracts.ProgramParams(
        d=d, k=q, m=4, n=8, n_feature_shards=2, n_workers_mesh=4,
    )
    viols, _ = sh_mod.check_shardings(
        contract.sharding, params,
        program="mutant_replicated_dk",
        dense_dim=contract.dense_dim(params),
        in_avals=[arg],
        in_shardings=jax.tree_util.tree_leaves(
            compiled.input_shardings
        ),
        out_avals=[arg],
        out_shardings=jax.tree_util.tree_leaves(
            compiled.output_shardings
        ),
        hlo_text=compiled.as_text(),
    )
    return viols


def _mutant_dist_dense_gram() -> list[contracts.Violation]:
    """The distributed-solve regression ISSUE 15's gate exists for: a
    'distributed' eigensolve that assembles the full row set and psums
    the DENSE d x d Gram over the features axis instead of iterating
    on the row-sharded factors. Both op kinds (all-gather, all-reduce)
    are in the dist_solve contract's allowed set — the PAYLOAD bound
    is what must catch it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.parallel.mesh import (
        make_mesh,
        shard_map,
    )

    mesh = make_mesh(num_workers=4, num_feature_shards=2)
    d = 2 * _D

    def dense_solve(c):  # (d_local, f) factor shard -> dense d x d
        full = jax.lax.all_gather(c, "features", axis=0, tiled=True)
        g = jnp.matmul(full, full.T)
        return jax.lax.psum(g, "features")

    f = jax.jit(shard_map(
        dense_solve, mesh=mesh, in_specs=P("features", None),
        out_specs=P(), check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((d, 8), jnp.float32)
    ).compile().as_text()
    contract = contracts.CONTRACTS["dist_solve"]
    params = contracts.ProgramParams(
        d=d, k=2, m=4, n_feature_shards=2, n_workers_mesh=4,
    )
    viols, _ = contracts.check_collectives(
        contract, params, hlo, program="mutant_dist_dense_gram"
    )
    return viols


def _mutant_deflation_lane_gather() -> list[contracts.Violation]:
    """The parallel-deflation regression ISSUE 18's gate exists for: a
    lane that all-gathers the full DEFLATED operand over 'features'
    (d-wide rows on every device) instead of moving its own
    (d_local, k/L) panel over 'components'. all-gather is in the
    deflation_solve contract's allowed set — the PAYLOAD bound (the
    d_local * k lane gather / factor stack) is what must catch it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.parallel.mesh import (
        FEATURE_AXIS,
        make_component_mesh,
        shard_map,
    )

    mesh = make_component_mesh(4, 2)
    d, r = 2 * _D, 8

    def lane_sweep(c):  # (d_local, r) deflated operand shard
        full = jax.lax.all_gather(c, FEATURE_AXIS, axis=0, tiled=True)
        return jnp.matmul(full.T, full)

    f = jax.jit(shard_map(
        lane_sweep, mesh=mesh, in_specs=P(FEATURE_AXIS, None),
        out_specs=P(), check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((d, r), jnp.float32)
    ).compile().as_text()
    contract = contracts.CONTRACTS["deflation_solve"]
    params = contracts.ProgramParams(
        d=d, k=8, m=1, n_feature_shards=2, n_workers_mesh=4,
        sketch_width=r, components=4,
    )
    viols, _ = contracts.check_collectives(
        contract, params, hlo, program="mutant_deflation_lane_gather"
    )
    return viols


def _mutant_tree_payload_drift() -> list[contracts.Violation]:
    """A tree tier moving the flat m-wide factor STACK instead of the
    merged (d, k) basis — the op kind (all-reduce) is in the tree
    contract's allowed set, so only the cost model's per-op byte
    budget can catch the drift."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.analysis import costmodel
    from distributed_eigenspaces_tpu.parallel.mesh import shard_map
    from distributed_eigenspaces_tpu.parallel.topology import (
        MergeTopology,
        make_tiered_mesh,
    )

    topo = MergeTopology((("chip", 2), ("host", 2)))
    mesh = make_tiered_mesh(topo)

    def stack_round(vs):  # psum the whole (m, d, k) stack on a tier
        return jax.lax.psum(vs, "chip")

    f = jax.jit(shard_map(
        stack_round, mesh=mesh, in_specs=P(), out_specs=P(),
        check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((4, _D, 2), jnp.float32)
    ).compile().as_text()
    params = contracts.ProgramParams(
        d=_D, k=2, m=4, n=8, tier_fan_ins=topo.fan_ins,
        tier_axes=topo.names,
    )
    viols, _ = costmodel.check_cost_bound(
        "tree_merge", params, hlo,
        program="mutant_tree_payload_drift",
    )
    return viols


def _mutant_population_payload() -> list[contracts.Violation]:
    """The population-scale regression ISSUE 16's gate exists for: a
    cohort reduce that all-gathers the POPULATION-sized stack instead
    of the sampled cohort's — the op kind (all-gather) is in the
    population_merge contract's allowed set, so the PAYLOAD bound
    (m := cohort, never population) is what must catch it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.parallel.mesh import (
        WORKER_AXIS,
        make_mesh,
        shard_map,
    )

    mesh = make_mesh(num_workers=8)
    population = 1024  # vs the declared cohort of 16

    def population_reduce(stack_shard):
        full = jax.lax.all_gather(
            stack_shard, WORKER_AXIS, axis=0, tiled=True
        )
        return full.mean(axis=0)

    f = jax.jit(shard_map(
        population_reduce, mesh=mesh,
        in_specs=P(WORKER_AXIS, None, None), out_specs=P(),
        check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((population, _D, 2), jnp.float32)
    ).compile().as_text()
    contract = contracts.CONTRACTS["population_merge"]
    params = contracts.ProgramParams(
        d=_D, k=2, m=16, n_workers_mesh=8,
    )
    viols, _ = contracts.check_collectives(
        contract, params, hlo, program="mutant_population_payload"
    )
    return viols


def _mutant_pallas_full_block() -> list[contracts.Violation]:
    """The tiling regression ISSUE 17's kernel gate exists for: a
    'tiled' Pallas kernel whose index map pins the FULL (rows, d)
    operand as ONE block. Legal Pallas — it compiles, runs, and is
    bit-exact — but every grid step streams the whole operand through
    VMEM, so only the per-ref tile budget can catch it."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    d, rows, k = 1024, 256, 8

    def kernel(x_ref, v_ref, o_ref):
        o_ref[:] = jax.lax.dot_general(
            x_ref[:], v_ref[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def project(x, v):
        return pl.pallas_call(
            kernel,
            grid=(1,),
            in_specs=[
                pl.BlockSpec((rows, d), lambda i: (0, 0)),
                pl.BlockSpec((d, k), lambda i: (0, 0)),
            ],
            out_specs=pl.BlockSpec((rows, k), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, k), jnp.float32),
            interpret=True,
        )(x, v)

    jitted = jax.jit(project)
    args = (
        jax.ShapeDtypeStruct((rows, d), jnp.float32),
        jax.ShapeDtypeStruct((d, k), jnp.float32),
    )
    contract = contracts.CONTRACTS["serve_pallas"]
    params = contracts.ProgramParams(d=d, k=k, rows=rows)
    viols, _ = contracts.check_pallas(
        contract, params, jitted.trace(*args).jaxpr,
        program="mutant_pallas_full_block",
    )
    return viols


_FIXTURE_BLOCKING = '''
import threading, time
class Worker:
    def __init__(self):
        self._lock = threading.Lock()
    def drain(self):
        with self._lock:
            self._thread.join()
            time.sleep(0.1)
'''

_FIXTURE_LOCK_ORDER = '''
import threading
class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
    def swap(self):
        with self._lock:
            with self._aux:
                pass
'''

_FIXTURE_UNGUARDED = '''
import threading
class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
    def bump(self):
        with self._lock:
            self.count += 1
    def reset(self):
        self.count = 0
'''

_FIXTURE_HOST_SYNC = '''
import jax
import numpy as np
@jax.jit
def step(x):
    if x:
        return float(x)
    return np.asarray(x).item()
'''


def _ast_mutant(fixture: str, linter) -> Callable[[], list]:
    def run() -> list[contracts.Violation]:
        return linter(fixture, "seeded_fixture.py")

    return run


def _mutant_plan_infeasible() -> list:
    """A hand-built ``plan-v1`` whose predicted host-tier merge budget
    (640 ms/round) exceeds the workload's declared round deadline
    (50 ms): a planner that accepted this plan would schedule a merge
    that can never close its rounds. The planner's self-check must
    refuse it loudly (ISSUE 19 — the ``plan_infeasible_accepted``
    witness)."""
    from distributed_eigenspaces_tpu.analysis import planner

    plan = {
        "schema": planner.PLAN_SCHEMA,
        "plan_id": "plan-seeded-infeasible",
        "workload": {
            "d": 1024, "k": 8, "m": 16, "n": 64,
            "qps": 100.0, "slo_p99_ms": 200.0,
            "round_deadline_ms": 50.0,
        },
        "chosen": {
            "config_overrides": {"merge_interval": 1},
            "predicted": {
                "fit_tiers": {
                    "host": {
                        "fan_in": 2,
                        "wire_bytes_per_round": 8_000_000_000,
                        "modeled_ms_per_round": 640.0,
                        "assumed_gb_per_sec": 12.5,
                    },
                },
                "serve": {"predicted_p99_ms": 120.0},
            },
        },
    }
    return planner.self_check(plan)


def _mutant_wire_dtype_drift() -> list[contracts.Violation]:
    """A tiered merge whose tiers are DECLARED int8 on the wire but
    whose basis gather ships full-width fp32 (ISSUE 20): the codec was
    dropped — or never wired in — and the compression the policy
    promises silently never happens. Both halves of the
    ``collective-wire-dtype`` rule must fire: no s8 data-mover exists
    for the declared tiers, and a wide f32 gather rides a replica
    group that only compressed tiers own."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from distributed_eigenspaces_tpu.parallel.mesh import shard_map
    from distributed_eigenspaces_tpu.parallel.topology import (
        MergeTopology,
        make_tiered_mesh,
    )

    topo = MergeTopology((("chip", 2), ("host", 2)))
    mesh = make_tiered_mesh(topo)

    def drifted_round(v):  # (d/2, k) -> fp32 gather on an int8 tier
        return jax.lax.all_gather(v, "chip", axis=0, tiled=True)

    f = jax.jit(shard_map(
        drifted_round, mesh=mesh,
        in_specs=P("chip"), out_specs=P(),
        check_vma=False,
    ))
    hlo = f.lower(
        jnp.zeros((_D // 2, 2), jnp.float32)
    ).compile().as_text()
    contract = contracts.CONTRACTS["tree_merge"]
    params = contracts.ProgramParams(
        d=_D, k=2, m=4, n=8,
        tier_fan_ins=topo.fan_ins, tier_axes=topo.names,
        tier_wire_dtypes=("int8", "int8"),
    )
    viols, _ = contracts.check_collectives(
        contract, params, hlo, program="mutant_wire_dtype_drift"
    )
    return viols


#: mutation name -> (expected rule, runner). Every violation class the
#: analyzer claims to catch has exactly one seeded witness here.
MUTATIONS: dict[str, tuple[str, Callable[[], list]]] = {
    "dense_collective": ("collective-op", _mutant_dense_collective),
    "tree_dense_collective": (
        "collective-payload", _mutant_tree_dense_collective
    ),
    "dense_temp": ("dense-buffer", _mutant_dense_temp),
    "baked_constant": ("baked-constant", _mutant_baked_constant),
    "replicated_dk": ("silent-replication", _mutant_replicated_dk),
    "dist_dense_gram": (
        "collective-payload", _mutant_dist_dense_gram
    ),
    "deflation_lane_gather": (
        "collective-payload", _mutant_deflation_lane_gather
    ),
    "tree_payload_drift": (
        "cost-bound", _mutant_tree_payload_drift
    ),
    "population_payload": (
        "collective-payload", _mutant_population_payload
    ),
    "pallas_full_block": (
        "pallas-block", _mutant_pallas_full_block
    ),
    "plan_infeasible_accepted": (
        "plan-infeasible", _mutant_plan_infeasible
    ),
    "wire_dtype_drift": (
        "collective-wire-dtype", _mutant_wire_dtype_drift
    ),
    "blocking_under_lock": ("blocking-under-lock", _ast_mutant(
        _FIXTURE_BLOCKING, ast_lints.lint_concurrency_source
    )),
    "lock_order": ("lock-order", _ast_mutant(
        _FIXTURE_LOCK_ORDER, ast_lints.lint_concurrency_source
    )),
    "unguarded_shared_write": ("unguarded-shared-write", _ast_mutant(
        _FIXTURE_UNGUARDED, ast_lints.lint_concurrency_source
    )),
    "host_sync": ("host-sync", _ast_mutant(
        _FIXTURE_HOST_SYNC, ast_lints.lint_host_sync_source
    )),
    "traced_branch": ("traced-branch", _ast_mutant(
        _FIXTURE_HOST_SYNC, ast_lints.lint_host_sync_source
    )),
}


def run_mutation_checks() -> tuple[bool, list[dict]]:
    """Run every seeded mutation; each must be CAUGHT with the
    expected rule. Returns (all_caught, per-mutation records)."""
    records = []
    all_ok = True
    for name, (rule, runner) in MUTATIONS.items():
        viols = runner()
        hits = [v for v in viols if v.rule == rule]
        caught = bool(hits)
        all_ok &= caught
        records.append({
            "mutation": name,
            "expected_rule": rule,
            "caught": caught,
            "n_violations": len(viols),
            "messages": [v.format() for v in hits[:2]],
        })
    return all_ok, records
