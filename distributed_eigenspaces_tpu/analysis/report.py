"""Aggregation: run the passes, emit one bench-style machine-readable
report.

Three entry points:

- :func:`run_analysis` — the full static audit (program matrix +
  lints), what ``scripts/analyze.py --all`` and CI stage "analyze"
  emit;
- :func:`run_mutation_report` — the self-test (:mod:`.mutations`);
- :func:`engine_report` — audit a LIVE serving engine's
  already-compiled bucket programs (zero extra compiles): this is the
  report ``MetricsLogger.attach_analysis`` lands in
  ``summary()["analysis"]`` so a bench record carries the contract
  verdict alongside its latency numbers.

Report schema ``analysis-v2`` (ISSUE 13): per-program entries carry
``shardings`` (declared-PartitionSpec audit) and ``costs`` (measured
FLOPs/HBM/per-axis collective bytes + the closed-form byte budget)
sections alongside the v1 keys. bench ``--compare`` treats
``analysis`` as a passthrough section, never a metric, so v1 records
compare cleanly against v2 ones — a schema mismatch is surfaced as a
loud note, not a crash (tests/test_bench_compare.py pins both
directions).
"""

from __future__ import annotations

SCHEMA = "analysis-v2"


def _violations_json(viols) -> list[dict]:
    return [
        {
            "program": v.program,
            "rule": v.rule,
            "message": v.message,
            "location": v.location,
        }
        for v in viols
    ]


def run_analysis(
    program_names=None,
    *,
    lints: bool = True,
    root: str | None = None,
) -> dict:
    """The full static audit. ``program_names=None`` runs the whole
    matrix; pass a subset for a fast targeted run."""
    from distributed_eigenspaces_tpu.analysis import (
        ast_lints,
        contracts,
        programs,
    )

    names = list(program_names or programs.PROGRAMS)
    report: dict = {
        "schema": SCHEMA,
        "programs": {},
        "lints": {},
        "ok": True,
        "n_violations": 0,
    }
    for name in names:
        built = programs.build_program(name)
        viols, detail = contracts.check_program(built)
        detail["violations"] = _violations_json(viols)
        report["programs"][name] = detail
        report["n_violations"] += len(viols)
    if lints:
        for key, runner in (
            ("concurrency", ast_lints.lint_concurrency),
            ("host_sync", ast_lints.lint_host_sync),
        ):
            viols = runner(root)
            report["lints"][key] = {
                "ok": not viols,
                "violations": _violations_json(viols),
            }
            report["n_violations"] += len(viols)
    report["ok"] = report["n_violations"] == 0
    return report


def run_mutation_report() -> dict:
    """The gate's self-test: every seeded violation class must be
    caught with its expected rule."""
    from distributed_eigenspaces_tpu.analysis import mutations

    ok, records = mutations.run_mutation_checks()
    return {"schema": SCHEMA, "ok": ok, "mutations": records}


def engine_report(engine) -> dict:
    """Contract audit of a live ``TransformEngine``'s compiled bucket
    programs. Reads the engine's compile cache directly — no compiles,
    so attaching this to a bench summary costs parsing only.

    The memory pass runs only on buckets whose row count sits below
    ``d`` (the premise that makes the dense-shape rule exact — a
    (rows, d) activation with rows >= d is legitimately 'dense' by
    shape and proves nothing)."""
    from distributed_eigenspaces_tpu.analysis import contracts
    from distributed_eigenspaces_tpu.analysis import (
        shardings as shardings_mod,
    )

    # a sharded-basis engine's project/residual kernels legitimately
    # psum over 'features' — audit those against the dist_serve
    # contract, not the zero-collective replicated-basis one
    kind_key = (
        "dist_serve"
        if getattr(engine, "basis_spec", None) is not None
        else "serve_transform"
    )
    contract = contracts.CONTRACTS[kind_key]
    out: dict = {
        "schema": SCHEMA,
        "contract": contract.name,
        "programs": {},
        "ok": True,
        "n_violations": 0,
    }
    for (kind, rows), compiled in sorted(engine._cache.items()):
        params = contracts.ProgramParams(
            d=engine.d, k=engine.k, rows=rows
        )
        name = f"serve_{kind}_rows{rows}"
        hlo = compiled.as_text()
        viols, col = contracts.check_collectives(
            contract, params, hlo, program=name
        )
        entry: dict = {"collectives": col}
        # live engines expose compiled executables, not traced avals —
        # the leaf-level sharding audit runs in the program matrix;
        # here the HLO annotation census keeps the layout visible
        entry["shardings"] = {
            "annotations": shardings_mod.parse_hlo_shardings(hlo),
        }
        if rows < contract.dense_dim(params):
            mv, mem = contracts.check_memory(
                contract, params, program=name, hlo_text=hlo
            )
            viols += mv
            entry["memory"] = mem
        entry["ok"] = not viols
        entry["violations"] = _violations_json(viols)
        out["programs"][name] = entry
        out["n_violations"] += len(viols)
    out["ok"] = out["n_violations"] == 0
    return out
