"""Declarative program contracts + the checkers that enforce them.

A **contract** states, per program kind, what the compiled artifact is
allowed to look like — the structural claims the docs make, as data:

- *collective schedule*: which collective op kinds may appear in the
  SPMD-partitioned HLO and the per-device payload ceiling as a
  function of the program's ``(d, k, m, B, …)`` parameters. The scan
  family moves ONLY the ``(m, d, k)`` factor stack; the
  feature-sharded cores add k-wide reductions bounded by the factor
  stack; fleet and serve programs contain ZERO collectives by
  construction.
- *memory footprint*: ``factor_only`` programs may not hold ANY buffer
  (jaxpr aval or per-device HLO buffer) with two or more axes each
  ``>= dense_dim`` — the shape class a materialized ``d x d``
  projector/Gram falls into. ``dense_state`` programs (the solo/fleet
  trainers whose carried state IS ``sigma_tilde (d, d)``) skip the
  shape rule but still report ``memory_analysis()`` numbers.
- *baked constants*: no closure-captured array constant above
  ``max_const_elems`` may ride in the jaxpr — a baked-in basis both
  recompiles on every publish and poisons ``CompileCache`` keys.

Checkers return :class:`Violation` records (never raise on contract
breach — the driver aggregates and formats), each naming the program,
the rule, and the offending HLO line / jaxpr eqn, so a CI failure is
actionable from the message alone.

The audited config matrix deliberately keeps every non-feature
dimension (``m``, ``n``, ``T``, ``B``, ``k``, serve rows) BELOW
``dense_dim`` — that is what makes "two axes >= dense_dim" exactly the
dense-matrix shape class with zero false positives; ``check_program``
validates the premise loudly rather than trusting the matrix author.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Callable

from distributed_eigenspaces_tpu.analysis import hlo as _hlo
from distributed_eigenspaces_tpu.analysis.shardings import (
    WILD,
    DeclaredBuffer,
    ShardingContract,
)


@dataclass(frozen=True)
class ProgramParams:
    """The shape parameters a contract's bounds are functions of."""

    d: int
    k: int
    m: int = 1
    n: int = 1
    T: int = 1
    B: int = 1
    rows: int = 1
    n_feature_shards: int = 1
    n_workers_mesh: int = 1
    sketch_width: int = 0
    #: parallel-deflation lane count (deflation_solve programs only):
    #: the 'components' mesh-axis size the k eigenvector lanes are
    #: model-parallel over — lane width is k / components
    components: int = 1
    #: merge-tree fan-ins leaf->root (tree_merge programs only): the
    #: tier-local Gram psum is (f*k)^2 per tier
    tier_fan_ins: tuple[int, ...] = ()
    #: merge-tree tier AXIS NAMES leaf->root — the mesh axes the
    #: sharding contract requires the tree's inputs sharded over and
    #: the cost model attributes per-tier wire bytes to
    tier_axes: tuple[str, ...] = ()
    #: per-tier WIRE dtype declarations leaf->root (ISSUE 20), aligned
    #: with ``tier_axes``: which codec each tier's data-moving
    #: collectives must carry on the wire ("fp32" / "bf16" / "int8").
    #: Empty = no wire policy declared — the dtype rule is skipped.
    #: Reductions (psum) are exempt by design: accumulation is fp32
    #: even on compressed tiers
    tier_wire_dtypes: tuple[str, ...] = ()

    @property
    def d_local(self) -> int:
        return self.d // max(self.n_feature_shards, 1)


@dataclass(frozen=True)
class Violation:
    """One contract breach, formatted to be actionable from CI output
    alone: program + rule + where."""

    program: str
    rule: str  # collective-op / collective-payload / dense-buffer / ...
    message: str
    location: str = ""  # HLO line, jaxpr eqn, or file:line for lints

    def format(self) -> str:
        loc = f" [{self.location}]" if self.location else ""
        return f"{self.program}: {self.rule}: {self.message}{loc}"


@dataclass(frozen=True)
class ProgramContract:
    """What one program kind's compiled artifact must look like."""

    name: str
    description: str
    #: collective op kinds allowed in the partitioned HLO (empty =
    #: zero collectives by construction)
    allowed_collectives: frozenset[str] = frozenset()
    #: per-device payload ceiling in ELEMENTS as a function of params;
    #: None with empty allowed_collectives (nothing to bound)
    max_payload_elems: Callable[[ProgramParams], int] | None = None
    #: a sharded build must actually contain collectives — guards
    #: against the audit passing vacuously on an unsharded build
    require_collectives: bool = False
    #: "factor_only": no buffer with >= 2 axes each >= dense_dim;
    #: "dense_state": the carried state is legitimately d x d (solo /
    #: fleet trainers) — shape rule skipped, footprint still reported
    memory_policy: str = "factor_only"
    #: the dimension the dense-buffer rule measures against (defaults
    #: to the PER-DEVICE feature width — d_local on feature-sharded
    #: programs, d elsewhere)
    dense_dim: Callable[[ProgramParams], int] = field(
        default=lambda p: p.d_local
    )
    #: largest array constant allowed baked into the jaxpr, in elements
    max_const_elems: Callable[[ProgramParams], int] = field(
        default=lambda p: p.d
    )
    #: declared PartitionSpecs (ISSUE 13): which buffers must be
    #: sharded over which mesh axes — the silent-replication gate.
    #: None = no sharding contract (checked programs without one are
    #: skipped with a named reason, never passed vacuously)
    sharding: ShardingContract | None = None
    #: Pallas kernel tile budget (ISSUE 17): ceiling in ELEMENTS on
    #: every block ref the kernel jaxpr touches — inputs, outputs, and
    #: scratch alike. A kernel whose index map pins a full (rows, d)
    #: operand as one block is legal Pallas and still runs; only this
    #: bound catches that it silently stopped tiling. None = no Pallas
    #: contract (pallas_call eqns in such programs are not audited)
    max_block_elems: Callable[[ProgramParams], int] | None = None
    #: a Pallas-contract program must actually contain pallas_call
    #: eqns — guards against the audit passing vacuously on a build
    #: that fell back to the XLA twin
    require_pallas: bool = False


def _factor_stack(p: ProgramParams) -> int:
    """The merge's gathered factor stack — the payload ceiling every
    trainer contract quotes: ``m * d_local * max(k, sketch_width)``."""
    return p.m * p.d_local * max(p.k, p.sketch_width)


def _deflation_stack(p: ProgramParams) -> int:
    """Parallel deflation's payload ceiling (ISSUE 18): the largest
    thing a device may move is the cross-lane gather of its own
    ``(d_local, k/L)`` panel — gathered size ``d_local * k`` — or the
    merge's worker factor stack when the operand is an m-wide concat.
    The deflation corrections themselves are ``(L, kb, kb)`` blocks
    (k x k class), strictly below both; a lane gathering the full
    DEFLATED operand over features is d-wide and blows this bound."""
    return max(_factor_stack(p), p.d_local * p.k)


def _tree_bound(p: ProgramParams) -> int:
    """The tiered tree's payload ceiling: every tier moves at most the
    single ``(d, k)`` basis (all-to-all of the row-split factors /
    all-gather at the tier boundary) or the tier-local ``(f*k, f*k)``
    factor Gram (one psum) — never the flat route's m-wide factor stack
    and never a dense ``d x d``."""
    kf = max(p.k, p.sketch_width)
    gram = max(((f * kf) ** 2 for f in p.tier_fan_ins), default=0)
    return max(p.d_local * kf, gram)


# -- the registry ------------------------------------------------------------

#: Contract per program KIND (programs.py maps each config-matrix entry
#: to one of these). Declaring a contract for a new program = one entry
#: here + a builder in programs.py (docs/ANALYSIS.md walks through it).
CONTRACTS: dict[str, ProgramContract] = {
    "scan_fit": ProgramContract(
        name="scan_fit",
        description=(
            "whole-fit scan (solo / masked / pipelined / interval): the "
            "only collective is the per-step all-gather of the "
            "(m, d, k) factor stack; dense d x d state is carried but "
            "never crosses the mesh"
        ),
        allowed_collectives=frozenset({"all-gather"}),
        max_payload_elems=_factor_stack,
        require_collectives=True,
        memory_policy="dense_state",
        sharding=ShardingContract(buffers=(
            DeclaredBuffer(
                "step blocks", "in",
                dims=lambda p: (WILD, p.m, p.n, p.d),
                spec=lambda p: (None, "workers", None, None),
            ),
            DeclaredBuffer(
                "carried state", "in",
                dims=lambda p: (p.d, p.d),
                spec=lambda p: (None, None),
            ),
        )),
    ),
    "feature_sharded": ProgramContract(
        name="feature_sharded",
        description=(
            "feature-sharded scan/sketch cores: k-wide reductions and "
            "the per-shard factor gather only, every payload bounded "
            "by the factor stack; NO dense d x d buffer exists on any "
            "device (the low-rank carry is the whole point)"
        ),
        allowed_collectives=frozenset({"all-gather", "all-reduce"}),
        max_payload_elems=_factor_stack,
        require_collectives=True,
        memory_policy="factor_only",
        sharding=ShardingContract(
            buffers=(
                DeclaredBuffer(
                    "feature-sharded basis", "in",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                ),
                DeclaredBuffer(
                    "feature blocks", "in",
                    dims=lambda p: (WILD, p.m, p.n, p.d),
                    spec=lambda p: (None, "workers", None, "features"),
                ),
                DeclaredBuffer(
                    "feature-sharded basis", "out",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                ),
            ),
            # THE d-ceiling rule: no device may hold a full-d buffer
            # with >= 2 companion elements — an un-sharded (d, k)
            replicated_axis_floor=lambda p: p.d,
        ),
    ),
    "tree_merge": ProgramContract(
        name="tree_merge",
        description=(
            "tiered-mesh tree fit (ISSUE 12): per-tier sharded merge "
            "updates only — all-to-all of the row-split (d, k) "
            "factors, one all-reduce of the (f*k, f*k) tier Gram, and "
            "the (d, k) basis all-gather at each tier boundary; the "
            "flat route's m-wide factor-stack gather must NOT appear, "
            "and no collective ever moves a dense d x d"
        ),
        allowed_collectives=frozenset(
            {"all-to-all", "all-reduce", "all-gather"}
        ),
        max_payload_elems=_tree_bound,
        require_collectives=True,
        memory_policy="dense_state",
        sharding=ShardingContract(buffers=(
            DeclaredBuffer(
                "step blocks", "in",
                dims=lambda p: (WILD, p.m, p.n, p.d),
                # the worker dim factors over ALL tier axes (root-major
                # mesh; compared as a set)
                spec=lambda p: (None, p.tier_axes, None, None),
            ),
            DeclaredBuffer(
                "carried state", "in",
                dims=lambda p: (p.d, p.d),
                spec=lambda p: (None, None),
            ),
        )),
    ),
    "fleet_fit": ProgramContract(
        name="fleet_fit",
        description=(
            "B-tenant vmapped whole fit: pure data parallelism over "
            "the fleet axis — ZERO collectives by construction; dense "
            "per-tenant state is carried but never crosses the mesh"
        ),
        allowed_collectives=frozenset(),
        memory_policy="dense_state",
        sharding=ShardingContract(buffers=(
            DeclaredBuffer(
                "tenant blocks", "in",
                dims=lambda p: (p.B, WILD, WILD, WILD, p.d),
                spec=lambda p: ("workers", None, None, None, None),
            ),
            DeclaredBuffer(
                "tenant state", "in",
                dims=lambda p: (p.B, p.d, p.d),
                spec=lambda p: ("workers", None, None),
            ),
            DeclaredBuffer(
                "tenant state", "out",
                dims=lambda p: (p.B, p.d, p.d),
                spec=lambda p: ("workers", None, None),
            ),
            DeclaredBuffer(
                "tenant basis history", "out",
                dims=lambda p: (p.B, WILD, p.d, WILD),
                spec=lambda p: ("workers", None, None, None),
            ),
        )),
    ),
    "serve_transform": ProgramContract(
        name="serve_transform",
        description=(
            "serving kernels (project / reconstruct / residual): "
            "row-local matmuls — ZERO collectives, and factor-only "
            "memory (no program may materialize V V^T)"
        ),
        allowed_collectives=frozenset(),
        memory_policy="factor_only",
        dense_dim=lambda p: p.d,
        # serve kernels vary by transform (project takes (rows, d)+
        # basis, reconstruct takes (rows, k)+basis, residual (rows, d)
        # +(rows, k)) — every row-indexed buffer that appears must be
        # workers-sharded, the basis replicated BY DESIGN on this
        # BELOW-crossover engine (d fits one device). Above
        # ``cfg.eigh_crossover_d`` serving runs the sharded-basis
        # engine instead, whose ``dist_serve`` contract declares the
        # basis sharded over 'features' — that gate is what proves the
        # flip landed end-to-end
        sharding=ShardingContract(buffers=(
            DeclaredBuffer(
                "row activations", "in",
                dims=lambda p: (p.rows, p.d),
                spec=lambda p: ("workers", None),
                required=False,
            ),
            DeclaredBuffer(
                "row codes", "in",
                dims=lambda p: (p.rows, WILD),
                spec=lambda p: ("workers", None),
                required=False,
            ),
            DeclaredBuffer(
                "replicated basis", "in",
                dims=lambda p: (p.d, WILD),
                spec=lambda p: (None, None),
                required=False,
            ),
            DeclaredBuffer(
                "row outputs", "out",
                dims=lambda p: (p.rows, WILD),
                spec=lambda p: ("workers", None),
                required=False,
            ),
            DeclaredBuffer(
                "reconstructed rows", "out",
                dims=lambda p: (p.rows, p.d),
                spec=lambda p: ("workers", None),
                required=False,
            ),
            DeclaredBuffer(
                "row scalars", "out",
                dims=lambda p: (p.rows,),
                spec=lambda p: ("workers",),
                required=False,
            ),
        )),
    ),
    "dist_solve": ProgramContract(
        name="dist_solve",
        description=(
            "distributed eigensolve (ISSUE 15): merge / extract above "
            "the crossover as subspace iteration on row-sharded "
            "factors — the worker factor-stack gather plus k-wide "
            "psums over 'features' (CholeskyQR2 Grams, factor "
            "matvecs, the Rayleigh-Ritz reduce) only; nothing "
            "quadratic in m*k, nothing d-wide, never a dense d x d, "
            "and the result stays a (d_local, k) row shard"
        ),
        allowed_collectives=frozenset({"all-gather", "all-reduce"}),
        max_payload_elems=_factor_stack,
        require_collectives=True,
        memory_policy="factor_only",
        sharding=ShardingContract(
            buffers=(
                DeclaredBuffer(
                    "worker factor stack", "in",
                    dims=lambda p: (p.m, p.d, WILD),
                    spec=lambda p: ("workers", "features", None),
                    required=False,
                ),
                DeclaredBuffer(
                    "worker mask", "in",
                    dims=lambda p: (p.m,),
                    spec=lambda p: ("workers",),
                    required=False,
                ),
                DeclaredBuffer(
                    "row-sharded state factors", "in",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                    required=False,
                ),
                DeclaredBuffer(
                    "replicated spectrum", "in",
                    dims=lambda p: (p.sketch_width,),
                    spec=lambda p: (None,),
                    required=False,
                ),
                DeclaredBuffer(
                    "sharded eigenbasis", "out",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                ),
            ),
            # the d-ceiling rule, same as the sharded trainers: no
            # device may hold an un-sharded full-d buffer
            replicated_axis_floor=lambda p: p.d,
        ),
    ),
    "deflation_solve": ProgramContract(
        name="deflation_solve",
        description=(
            "parallel-deflation eigensolve (ISSUE 18): k lanes "
            "model-parallel over the 'components' mesh axis, each "
            "iterating a (d_local, k/L) block against the factor "
            "operand with deflation corrections from lower lanes. "
            "Collectives are the cross-lane gather of one lane panel "
            "(d_local * k gathered), the (L, kb, kb) correction-"
            "coefficient psums over 'features', and CholeskyQR2 / "
            "Rayleigh-Ritz k-wide Grams — corrections ride as k x k "
            "blocks, never d x d, never an above-floor replicated "
            "d x k; the result stays a (d_local, k) row shard"
        ),
        allowed_collectives=frozenset({"all-gather", "all-reduce"}),
        max_payload_elems=_deflation_stack,
        require_collectives=True,
        memory_policy="factor_only",
        sharding=ShardingContract(
            buffers=(
                DeclaredBuffer(
                    # THE components-axis witness: the per-lane seed
                    # blocks enter sharded over ('components',
                    # 'features') — this is what makes the audit
                    # non-vacuous on the new axis
                    "lane seed blocks", "in",
                    dims=lambda p: (
                        p.components,
                        p.d,
                        p.k // max(p.components, 1),
                    ),
                    spec=lambda p: ("components", "features", None),
                ),
                DeclaredBuffer(
                    "row-sharded state factors", "in",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                    required=False,
                ),
                DeclaredBuffer(
                    "replicated spectrum", "in",
                    dims=lambda p: (p.sketch_width,),
                    spec=lambda p: (None,),
                    required=False,
                ),
                DeclaredBuffer(
                    "worker factor stack", "in",
                    dims=lambda p: (p.m, p.d, WILD),
                    spec=lambda p: ("workers", "features", None),
                    required=False,
                ),
                DeclaredBuffer(
                    # replicated over 'components' (every lane slot
                    # computes the identical finish), row-sharded over
                    # 'features' — the same born-sharded output shape
                    # class as dist_solve
                    "sharded eigenbasis", "out",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                ),
            ),
            replicated_axis_floor=lambda p: p.d,
        ),
    ),
    "dist_serve": ProgramContract(
        name="dist_serve",
        description=(
            "sharded-basis serving kernels (above the crossover): the "
            "SAME row-local matmuls on (d_local, k) basis shards, "
            "plus the one rows x k projection psum the sharding makes "
            "necessary — no collective ever moves the basis, and the "
            "dense (d, k) never assembles on one device"
        ),
        allowed_collectives=frozenset({"all-reduce"}),
        # the projection / input-energy psums carry per-row k-wide (or
        # scalar) payloads — never anything d-wide
        max_payload_elems=lambda p: p.rows * max(p.k, 1),
        # reconstruct is row-local on the shards — zero collectives —
        # so presence is enforced per-kind by the sharding pass, not
        # globally here
        require_collectives=False,
        memory_policy="factor_only",
        dense_dim=lambda p: p.d,
        sharding=ShardingContract(
            buffers=(
                DeclaredBuffer(
                    "row activations", "in",
                    dims=lambda p: (p.rows, p.d),
                    spec=lambda p: ("workers", "features"),
                    required=False,
                ),
                DeclaredBuffer(
                    "row codes", "in",
                    dims=lambda p: (p.rows, WILD),
                    spec=lambda p: ("workers", None),
                    required=False,
                ),
                DeclaredBuffer(
                    "feature-sharded basis", "in",
                    dims=lambda p: (p.d, WILD),
                    spec=lambda p: ("features", None),
                    required=False,
                ),
                DeclaredBuffer(
                    "row outputs", "out",
                    dims=lambda p: (p.rows, WILD),
                    spec=lambda p: ("workers", None),
                    required=False,
                ),
                DeclaredBuffer(
                    "reconstructed rows", "out",
                    dims=lambda p: (p.rows, p.d),
                    spec=lambda p: ("workers", "features"),
                    required=False,
                ),
                DeclaredBuffer(
                    "row scalars", "out",
                    dims=lambda p: (p.rows,),
                    spec=lambda p: ("workers",),
                    required=False,
                ),
            ),
            replicated_axis_floor=lambda p: p.d,
        ),
    ),
    "serve_pallas": ProgramContract(
        name="serve_pallas",
        description=(
            "fused serve / solver Pallas kernels (ISSUE 17): the "
            "quantized dequant->project family and the fused "
            "matvec+Gram sweep — ZERO collectives, factor-only "
            "memory, and every kernel block ref (inputs, outputs, "
            "scratch) bounded by the VMEM tile budget; a kernel that "
            "maps the full (rows, d) operand into one block has "
            "silently stopped tiling"
        ),
        allowed_collectives=frozenset(),
        memory_policy="factor_only",
        dense_dim=lambda p: p.d,
        # 131072 f32 elems = 512 KiB per block ref — the serve tile
        # targets (256 rows x 512 d) at their ceiling; a full-operand
        # block at the kernel-audit shapes (256 x 1024) is 2x over
        max_block_elems=lambda p: 131072,
        require_pallas=True,
    ),
    "population_merge": ProgramContract(
        name="population_merge",
        description=(
            "population-scale cohort reduce (ISSUE 16): the hardened "
            "Byzantine-tolerant merge of one sampled cohort's (d, k) "
            "client summaries — the ONLY collective is the all-gather "
            "of the cohort-sharded factor stack, so per-round payloads "
            "are bounded by COHORT size (m := cohort), never by the "
            "population; the clip / trim / screen pipeline runs "
            "replicated post-gather and nothing population-sized or "
            "dense d x d ever crosses the mesh"
        ),
        allowed_collectives=frozenset({"all-gather"}),
        max_payload_elems=_factor_stack,
        require_collectives=True,
        memory_policy="factor_only",
        sharding=ShardingContract(buffers=(
            DeclaredBuffer(
                "cohort stack", "in",
                dims=lambda p: (p.m, p.d, WILD),
                spec=lambda p: ("workers", None, None),
            ),
            DeclaredBuffer(
                "arrival mask", "in",
                dims=lambda p: (p.m,),
                spec=lambda p: ("workers",),
            ),
            DeclaredBuffer(
                "merged basis", "out",
                dims=lambda p: (p.d, WILD),
                spec=lambda p: (None, None),
            ),
            DeclaredBuffer(
                "survivor mask", "out",
                dims=lambda p: (p.m,),
                spec=lambda p: (None,),
                required=False,
            ),
        )),
    ),
}


# -- checkers ----------------------------------------------------------------

#: the collective op kinds that MOVE data (and so carry a wire codec);
#: reductions (all-reduce) are exempt — accumulation stays fp32 even on
#: compressed tiers (int8 has no closed addition)
_DATA_MOVERS = frozenset({"all-gather", "all-to-all"})


def _check_wire_dtypes(
    params: ProgramParams,
    ops,
    contract: ProgramContract,
    *,
    program: str,
) -> list[Violation]:
    """Rule ``collective-wire-dtype`` (ISSUE 20): the declared per-tier
    wire policy against the partitioned HLO's actual payload dtypes.

    Positive half: every tier declared non-fp32 must have at least one
    data-moving collective carrying that codec's HLO dtype token (bf16
    / s8) on a replica group of the tier's fan-in — a policy the
    program silently ignored is a compression that never happened.

    Negative half: an f32 data-mover above the ``d_local * kf / 2``
    elements floor whose replica-group size matches ONLY tiers declared
    compressed is a full-width payload on a wire the policy narrowed
    (the ``wire_dtype_drift`` mutant). The floor keeps the masked-
    weight gathers and int8 fp32 scale sidecars — both tiny and f32 by
    design — out of scope; ambiguous group sizes (a fan shared by an
    fp32 tier) are left alone rather than guessed at.

    bf16 caveat: backends without native bf16 collectives (the CPU
    audit rig) run float-normalization, which rewrites the bf16
    collective as an f32 one fed by the encode/decode convert pair —
    values are still bf16-rounded, only the emulation's local bytes
    widen. Both halves therefore accept an f32 mover whose operand
    list carries a ``convert`` as the normalized bf16 spelling; on
    TPU the collective stays bf16 and the check is exact. int8 has no
    such escape — s8 movers must appear verbatim everywhere.
    """
    from distributed_eigenspaces_tpu.analysis.costmodel import (
        parse_replica_groups,
    )
    from distributed_eigenspaces_tpu.parallel.wire import WIRE_HLO_TOKEN

    out: list[Violation] = []
    tiers = list(zip(
        params.tier_axes, params.tier_fan_ins, params.tier_wire_dtypes
    ))
    kf = max(params.k, params.sketch_width, 1)
    floor = params.d_local * kf // 2
    movers = []
    for o in ops:
        if o.op not in _DATA_MOVERS:
            continue
        groups = parse_replica_groups(o.line)
        gsize = len(groups[0]) if groups else None
        movers.append((o, gsize))

    def _bf16_normalized(o) -> bool:
        m = re.search(r"all-(?:gather|to-all)\(([^)]*)\)", o.line)
        return bool(m and "convert" in m.group(1))

    for axis, fan, dtype in tiers:
        if dtype == "fp32":
            continue
        token = WIRE_HLO_TOKEN[dtype]
        hit = any(
            (gsize is None or gsize == fan) and (
                o.dtype == token
                or (dtype == "bf16" and o.dtype == "f32"
                    and _bf16_normalized(o))
            )
            for o, gsize in movers
        )
        if not hit:
            out.append(Violation(
                program=program,
                rule="collective-wire-dtype",
                message=(
                    f"tier {axis!r} (fan-in {fan}) declares wire dtype "
                    f"{dtype!r} but no data-moving collective carries "
                    f"{token} on a group of {fan} — the declared "
                    "compression never reaches the wire "
                    f"(contract {contract.name!r})"
                ),
                location=f"tier_wire_dtypes[{axis!r}]={dtype!r}",
            ))
    for o, gsize in movers:
        if o.dtype != "f32" or o.elems <= floor or gsize is None:
            continue
        matched = [t for t in tiers if t[1] == gsize]
        if any(t[2] == "bf16" for t in matched) and _bf16_normalized(o):
            continue
        if matched and all(t[2] != "fp32" for t in matched):
            names = ", ".join(
                f"{t[0]}={t[2]}" for t in matched
            )
            out.append(Violation(
                program=program,
                rule="collective-wire-dtype",
                message=(
                    f"{o.op} moves {o.elems} f32 elems on a group of "
                    f"{gsize}, but every tier with that fan-in is "
                    f"declared compressed ({names}) — a full-width "
                    "fp32 payload is riding a wire the policy "
                    f"narrowed (contract {contract.name!r})"
                ),
                location=o.line.strip(),
            ))
    return out


def check_collectives(
    contract: ProgramContract,
    params: ProgramParams,
    hlo_text: str,
    *,
    program: str,
) -> tuple[list[Violation], dict]:
    """Pass 1: the per-program collective schedule against the
    partitioned HLO. Returns (violations, metrics)."""
    out: list[Violation] = []
    ops = _hlo.parse_collectives(hlo_text)
    metrics = {
        "n_collectives": len(ops),
        "max_payload_elems": max((o.elems for o in ops), default=0),
        "ops": {},
    }
    for o in ops:
        key = f"{o.op} {o.dtype}[{','.join(map(str, o.shape))}]"
        metrics["ops"][key] = metrics["ops"].get(key, 0) + 1
    for o in ops:
        if o.op not in contract.allowed_collectives:
            allowed = sorted(contract.allowed_collectives) or ["<none>"]
            out.append(Violation(
                program=program,
                rule="collective-op",
                message=(
                    f"{o.op} {o.dtype}{list(o.shape)} is not in the "
                    f"contract's allowed set {allowed} "
                    f"(contract {contract.name!r})"
                ),
                location=o.line.strip(),
            ))
    if contract.max_payload_elems is not None:
        bound = contract.max_payload_elems(params)
        for o in ops:
            if o.elems > bound:
                out.append(Violation(
                    program=program,
                    rule="collective-payload",
                    message=(
                        f"{o.op} payload {o.elems} elems exceeds the "
                        f"contract bound {bound} (= factor stack at "
                        f"d={params.d}, k={params.k}, m={params.m}) — "
                        "the merge must move factors, not dense "
                        f"matrices (contract {contract.name!r})"
                    ),
                    location=o.line.strip(),
                ))
    if contract.require_collectives and not ops:
        out.append(Violation(
            program=program,
            rule="collective-schedule",
            message=(
                "sharded build contains no collectives at all — the "
                "audit would pass vacuously (was the program actually "
                f"partitioned?) (contract {contract.name!r})"
            ),
        ))
    if params.tier_wire_dtypes:
        out.extend(_check_wire_dtypes(
            params, ops, contract, program=program
        ))
    return out, metrics


def _dense_shapes(
    shapes, threshold: int
) -> list[tuple[tuple[int, ...], str]]:
    """Shapes with >= 2 axes each >= threshold — the dense-matrix class
    a materialized d x d projector/Gram falls into."""
    hits = []
    for dtype, dims, where in shapes:
        if sum(1 for s in dims if s >= threshold) >= 2:
            hits.append((dims, where))
    return hits


def _iter_jaxpr_avals(closed_jaxpr):
    """Every aval in a closed jaxpr, recursively through sub-jaxprs
    (scan/while/cond bodies, pjit calls, shard_map inner jaxprs —
    where shapes are PER-DEVICE). Yields (aval, eqn_str)."""
    import jax.core  # noqa: F401  (aval types live on the objects)

    seen: set[int] = set()

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for v in list(jaxpr.invars) + list(jaxpr.constvars):
            yield getattr(v, "aval", None), "<input>"
        for eqn in jaxpr.eqns:
            es = None
            for v in eqn.outvars:
                if es is None:
                    es = f"{eqn.primitive.name}"
                yield getattr(v, "aval", None), es
            for p in eqn.params.values():
                for sub in _sub_jaxprs(p):
                    yield from walk(sub)

    def _sub_jaxprs(param):
        out = []
        stack = [param]
        while stack:
            p = stack.pop()
            if hasattr(p, "jaxpr") and hasattr(p.jaxpr, "eqns"):
                out.append(p.jaxpr)  # ClosedJaxpr
            elif hasattr(p, "eqns"):
                out.append(p)  # bare Jaxpr
            elif isinstance(p, (tuple, list)):
                stack.extend(p)
        return out

    inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    yield from walk(inner)


def check_memory(
    contract: ProgramContract,
    params: ProgramParams,
    *,
    program: str,
    hlo_text: str | None = None,
    closed_jaxpr=None,
    memory_stats=None,
) -> tuple[list[Violation], dict]:
    """Pass 2: the memory-footprint contract. Walks the closed jaxpr
    (global + per-device shapes via sub-jaxprs) and the compiled HLO's
    per-device buffer shapes; ``factor_only`` programs may not hold any
    dense ``>= (t, t)`` buffer. ``memory_analysis()`` aggregates ride
    along in the metrics either way."""
    out: list[Violation] = []
    t = contract.dense_dim(params)
    # the premise that makes the shape rule exact: every non-feature
    # config dimension sits below the threshold (see module docstring)
    small = {"m": params.m, "n": params.n, "T": params.T, "B": params.B,
             "k": params.k, "rows": params.rows}
    offenders = {nm: v for nm, v in small.items() if v >= t}
    if offenders:
        raise ValueError(
            f"audit config for {program!r} breaks the dense-shape "
            f"premise: {offenders} >= dense_dim {t} — shrink the "
            "audited shapes (analysis/programs.py) so the two-large-"
            "axes rule stays exactly the dense-matrix class"
        )
    metrics: dict = {"dense_dim": t}
    if memory_stats is not None:
        metrics["temp_bytes_per_device"] = int(
            getattr(memory_stats, "temp_size_in_bytes", 0)
        )
        metrics["argument_bytes_per_device"] = int(
            getattr(memory_stats, "argument_size_in_bytes", 0)
        )
        metrics["output_bytes_per_device"] = int(
            getattr(memory_stats, "output_size_in_bytes", 0)
        )
    if contract.memory_policy != "factor_only":
        metrics["policy"] = contract.memory_policy
        return out, metrics
    metrics["policy"] = "factor_only"
    if closed_jaxpr is not None:
        for aval, where in _iter_jaxpr_avals(closed_jaxpr):
            dims = tuple(getattr(aval, "shape", ()) or ())
            if sum(1 for s in dims if isinstance(s, int) and s >= t) >= 2:
                out.append(Violation(
                    program=program,
                    rule="dense-buffer",
                    message=(
                        f"jaxpr materializes a dense buffer "
                        f"{list(dims)} (>= 2 axes >= {t}) in a "
                        f"factor-only program — the d-ceiling "
                        "invariant is that no device ever holds a "
                        f"d x d (contract {contract.name!r})"
                    ),
                    location=f"jaxpr eqn: {where}",
                ))
    if hlo_text is not None:
        shapes = _hlo.parse_buffer_shapes(hlo_text)
        for dims, where in _dense_shapes(shapes, t):
            out.append(Violation(
                program=program,
                rule="dense-buffer",
                message=(
                    f"compiled HLO holds a per-device buffer "
                    f"{list(dims)} (>= 2 axes >= {t}) in a "
                    f"factor-only program (contract {contract.name!r})"
                ),
                location=where.strip(),
            ))
    return out, metrics


def check_consts(
    contract: ProgramContract,
    params: ProgramParams,
    closed_jaxpr,
    *,
    program: str,
) -> tuple[list[Violation], dict]:
    """Pass 3a: large baked-in constants. A closure-captured array in a
    jitted program recompiles on every value change AND poisons
    ``CompileCache`` keys (the key hashes shapes/knobs, not baked
    values — two runs with different baked bases would collide).
    Anything above ``max_const_elems`` should be an operand."""
    out: list[Violation] = []
    bound = contract.max_const_elems(params)
    consts = list(getattr(closed_jaxpr, "consts", ()) or ())
    sizes = []
    for c in consts:
        shape = tuple(getattr(c, "shape", ()) or ())
        elems = math.prod(shape) if shape else 1
        sizes.append(elems)
        if elems > bound:
            out.append(Violation(
                program=program,
                rule="baked-constant",
                message=(
                    f"jaxpr bakes in a {list(shape)} array constant "
                    f"({elems} elems > bound {bound}) — closure-"
                    "captured arrays recompile on every value change "
                    "and poison CompileCache keys; pass it as an "
                    f"operand instead (contract {contract.name!r})"
                ),
                location=f"const dtype={getattr(c, 'dtype', '?')}",
            ))
    return out, {
        "n_consts": len(consts),
        "max_const_elems": max(sizes, default=0),
        "const_bound": bound,
    }


def _iter_pallas_eqns(closed_jaxpr):
    """Every ``pallas_call`` eqn, recursively through sub-jaxprs
    (scan/while/cond bodies, pjit calls). Yields the eqn itself — its
    ``params['jaxpr']`` is the kernel jaxpr whose invars are the block
    refs (in/out blocks followed by scratch refs)."""
    seen: set[int] = set()

    def _sub_jaxprs(param):
        out = []
        stack = [param]
        while stack:
            p = stack.pop()
            if hasattr(p, "jaxpr") and hasattr(p.jaxpr, "eqns"):
                out.append(p.jaxpr)
            elif hasattr(p, "eqns"):
                out.append(p)
            elif isinstance(p, (tuple, list)):
                stack.extend(p)
        return out

    def walk(jaxpr):
        if id(jaxpr) in seen:
            return
        seen.add(id(jaxpr))
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                yield eqn
                continue  # the kernel jaxpr's refs are audited per-eqn
            for p in eqn.params.values():
                for sub in _sub_jaxprs(p):
                    yield from walk(sub)

    inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    yield from walk(inner)


def check_pallas(
    contract: ProgramContract,
    params: ProgramParams,
    closed_jaxpr,
    *,
    program: str,
) -> tuple[list[Violation], dict]:
    """Pass 4 (ISSUE 17): the Pallas tile budget. For every
    ``pallas_call`` eqn, bound the element count of EVERY kernel-jaxpr
    invar ref — in/out blocks and scratch uniformly — by
    ``max_block_elems``. The op-kind and dense-buffer passes cannot see
    this failure mode: a kernel whose index map pins the whole operand
    as one block compiles, runs, and produces exact answers — it just
    streams the full array through VMEM every grid step."""
    out: list[Violation] = []
    metrics: dict = {"n_pallas_calls": 0, "max_block_elems_seen": 0}
    if contract.max_block_elems is None:
        metrics["policy"] = "unchecked"
        return out, metrics
    bound = contract.max_block_elems(params)
    metrics["block_bound_elems"] = bound
    n_calls = 0
    worst = 0
    for eqn in _iter_pallas_eqns(closed_jaxpr):
        n_calls += 1
        kernel = eqn.params.get("jaxpr")
        kernel = getattr(kernel, "jaxpr", kernel)
        name = eqn.params.get("name_and_src_info", None)
        kname = getattr(name, "name", None) or str(
            name or "pallas_call"
        ).split(" ")[0]
        for i, var in enumerate(getattr(kernel, "invars", ())):
            shape = tuple(getattr(var.aval, "shape", ()) or ())
            elems = math.prod(shape) if shape else 1
            worst = max(worst, elems)
            if elems > bound:
                out.append(Violation(
                    program=program,
                    rule="pallas-block",
                    message=(
                        f"kernel block ref #{i} holds {list(shape)} = "
                        f"{elems} elems, over the tile budget {bound} "
                        "— the grid spec maps (nearly) the whole "
                        "operand into one block, so the kernel "
                        "streams the full array through VMEM every "
                        f"step (contract {contract.name!r})"
                    ),
                    location=f"pallas_call {kname!r}",
                ))
    metrics["n_pallas_calls"] = n_calls
    metrics["max_block_elems_seen"] = worst
    if contract.require_pallas and n_calls == 0:
        out.append(Violation(
            program=program,
            rule="pallas-presence",
            message=(
                "program contains no pallas_call at all — the tile "
                "audit would pass vacuously (did the build fall back "
                f"to the XLA twin?) (contract {contract.name!r})"
            ),
        ))
    return out, metrics


def check_program(built) -> tuple[list[Violation], dict]:
    """All static passes over one :class:`~.programs.BuiltProgram`:
    collectives + memory + baked constants + declared shardings +
    cost-model byte budgets. Returns ``(violations, metrics)`` — the
    driver aggregates."""
    from distributed_eigenspaces_tpu.analysis import costmodel
    from distributed_eigenspaces_tpu.analysis import shardings as _sh

    contract = CONTRACTS[built.contract]
    params = built.params
    hlo_text = built.hlo_text()
    violations: list[Violation] = []
    v, col = check_collectives(
        contract, params, hlo_text, program=built.name
    )
    violations += v
    jaxpr = built.jaxpr()
    v, mem = check_memory(
        contract, params,
        program=built.name,
        hlo_text=hlo_text,
        closed_jaxpr=jaxpr,
        memory_stats=built.memory_stats(),
    )
    violations += v
    v, const = check_consts(
        contract, params, jaxpr, program=built.name
    )
    violations += v
    v, pallas = check_pallas(
        contract, params, jaxpr, program=built.name
    )
    violations += v
    v, shard = _sh.check_built(built, contract)
    violations += v
    v, costs = costmodel.check_built(built)
    violations += v
    return violations, {
        "contract": contract.name,
        "ok": not violations,
        "collectives": col,
        "memory": mem,
        "consts": const,
        "pallas": pallas,
        "shardings": shard,
        "costs": costs,
    }
