"""Static program-contract analysis (ISSUE 10; sharding contracts +
cost model added in ISSUE 13).

Audits every program kind the system compiles — solo step/scan,
masked/pipelined scan, feature-sharded cores, tiered tree merges,
fleet vmapped fit, serve transform — against declarative **program
contracts**, without executing them, plus AST lints over the threaded
runtime. Six passes:

1. **collective-schedule contracts** (:mod:`.contracts` over
   :mod:`.hlo`): per-program expected collective op kinds and payload
   bounds as functions of ``(d, k, m, B)``, checked against the
   SPMD-partitioned HLO — the generalization of the old
   ``utils/collectives_audit`` tripwire into a registry;
2. **memory-footprint contracts** (:mod:`.contracts`): jaxpr +
   HLO-buffer + ``compiled.memory_analysis()`` walk asserting no
   per-device dense ``d x d`` temp exists in programs documented as
   factor-only — the enforcement mechanism the d-ceiling work
   (ROADMAP: d >= 32k distributed eigensolve) builds against;
3. **recompile/host-sync lints** (:mod:`.jaxpr_lints` /
   :mod:`.ast_lints`): large baked-in jaxpr constants (closure-captured
   arrays that should be operands — they also poison ``CompileCache``
   keys) and host-sync calls (``.item()``, ``np.asarray``, …) inside
   jitted code paths;
4. **concurrency lints** (:mod:`.ast_lints`): the repo's lock
   discipline over the threaded runtime — single lock order, no
   blocking calls while holding a lock, shared mutable attributes
   touched only under their documented lock;
5. **sharding contracts** (:mod:`.shardings`): declared PartitionSpecs
   for each program's d-carrying buffers, checked against the
   compiled executable's actual input/output shardings and the HLO
   annotations — SILENT REPLICATION of a contract-sharded buffer (the
   partitioner quietly all-gathering a ``(d, k)`` basis) fails with
   program + buffer shape + HLO location named;
6. **analytic cost model** (:mod:`.costmodel`): per-program FLOPs,
   HBM bytes, and per-mesh-axis collective bytes x hop counts parsed
   from compiled HLO, matched against closed-form models in
   ``ProgramParams``, budget-enforced per op, and diff-gated against
   the committed ``ANALYSIS_COSTS.json`` snapshot — the quantitative
   gate the d-ceiling work (ROADMAP: d >= 32k) plans against.

``scripts/analyze.py`` drives all six over the config matrix and the
gate is self-testing: :mod:`.mutations` seeds one violation per class
(a dense ``psum``, a ``d x d`` temp, a baked-in constant, a replicated
``(d, k)`` basis, a tree tier over its byte budget, a blocking call
under lock, …) and requires the checker to catch each one.

The package ``__init__`` stays lazy: :mod:`.hlo` and the lint modules
are import-cheap, but :mod:`.programs` pulls the trainer builders —
resolved on first attribute access. The old
``utils/collectives_audit`` shim (PR 10's back-compat path) is
RETIRED (ISSUE 13); its public names resolve here and in :mod:`.hlo`.
"""

from __future__ import annotations

_LAZY = {
    "hlo": "distributed_eigenspaces_tpu.analysis.hlo",
    "contracts": "distributed_eigenspaces_tpu.analysis.contracts",
    "programs": "distributed_eigenspaces_tpu.analysis.programs",
    "jaxpr_lints": "distributed_eigenspaces_tpu.analysis.jaxpr_lints",
    "ast_lints": "distributed_eigenspaces_tpu.analysis.ast_lints",
    "report": "distributed_eigenspaces_tpu.analysis.report",
    "mutations": "distributed_eigenspaces_tpu.analysis.mutations",
    "shardings": "distributed_eigenspaces_tpu.analysis.shardings",
    "costmodel": "distributed_eigenspaces_tpu.analysis.costmodel",
}

#: the audit's stable entry points, re-exported from :mod:`.hlo` so
#: callers of the retired ``utils/collectives_audit`` shim migrate to
#: ``from distributed_eigenspaces_tpu import analysis`` one-for-one
_HLO_API = (
    "AuditParseError",
    "CollectiveOp",
    "assert_no_dense_collective",
    "audit_compiled",
    "ici_step_model",
    "parse_collectives",
    "scaling_projection",
)

__all__ = sorted(_LAZY) + sorted(_HLO_API)


def __getattr__(name: str):
    import importlib

    if name in _LAZY:
        mod = importlib.import_module(_LAZY[name])
        globals()[name] = mod
        return mod
    if name in _HLO_API:
        mod = importlib.import_module(_LAZY["hlo"])
        obj = getattr(mod, name)
        globals()[name] = obj
        return obj
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
