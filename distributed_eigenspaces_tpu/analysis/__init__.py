"""Static program-contract analysis (ISSUE 10).

Audits every program kind the system compiles — solo step/scan,
masked/pipelined scan, feature-sharded cores, fleet vmapped fit, serve
transform — against declarative **program contracts**, without
executing them, plus AST lints over the threaded runtime. Four passes:

1. **collective-schedule contracts** (:mod:`.contracts` over
   :mod:`.hlo`): per-program expected collective op kinds and payload
   bounds as functions of ``(d, k, m, B)``, checked against the
   SPMD-partitioned HLO — the generalization of the old
   ``utils/collectives_audit`` tripwire into a registry;
2. **memory-footprint contracts** (:mod:`.contracts`): jaxpr +
   HLO-buffer + ``compiled.memory_analysis()`` walk asserting no
   per-device dense ``d x d`` temp exists in programs documented as
   factor-only — the enforcement mechanism the d-ceiling work
   (ROADMAP: d >= 32k distributed eigensolve) builds against;
3. **recompile/host-sync lints** (:mod:`.jaxpr_lints` /
   :mod:`.ast_lints`): large baked-in jaxpr constants (closure-captured
   arrays that should be operands — they also poison ``CompileCache``
   keys) and host-sync calls (``.item()``, ``np.asarray``, …) inside
   jitted code paths;
4. **concurrency lints** (:mod:`.ast_lints`): the repo's lock
   discipline over the threaded runtime — single lock order, no
   blocking calls while holding a lock, shared mutable attributes
   touched only under their documented lock.

``scripts/analyze.py`` drives all four over the config matrix and the
gate is self-testing: :mod:`.mutations` seeds one violation per class
(a dense ``psum``, a ``d x d`` temp, a baked-in constant, a blocking
call under lock, …) and requires the checker to catch each one.

The package ``__init__`` stays lazy: :mod:`.hlo` and the lint modules
are import-cheap, but :mod:`.programs` pulls the trainer builders —
resolved on first attribute access so the ``utils/collectives_audit``
back-compat shim can import :mod:`.hlo` without dragging the world in.
"""

from __future__ import annotations

_LAZY = {
    "hlo": "distributed_eigenspaces_tpu.analysis.hlo",
    "contracts": "distributed_eigenspaces_tpu.analysis.contracts",
    "programs": "distributed_eigenspaces_tpu.analysis.programs",
    "jaxpr_lints": "distributed_eigenspaces_tpu.analysis.jaxpr_lints",
    "ast_lints": "distributed_eigenspaces_tpu.analysis.ast_lints",
    "report": "distributed_eigenspaces_tpu.analysis.report",
    "mutations": "distributed_eigenspaces_tpu.analysis.mutations",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name])
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
