"""Jaxpr-level lints usable on ANY jitted callable (pass 3a outside
the program matrix).

The contract checkers (:func:`.contracts.check_consts`) run this rule
over the audited matrix; this module is the standalone entry point for
linting one function — e.g. a notebook probe, or the mutation
self-test seeding a closure-captured basis.

A closure-captured array constant in a jitted program is a double
hazard: the program recompiles whenever the VALUE changes (the shape
didn't, so nothing in the jit cache key saves you), and the persistent
``CompileCache`` keys hash shapes/knobs — two runs baking different
values would collide on one serialized program.
"""

from __future__ import annotations

import math

from distributed_eigenspaces_tpu.analysis.contracts import Violation

#: default ceiling, in elements: a k-vector of knobs is fine, a (d, k)
#: basis is not. Matrix programs get per-contract bounds instead.
DEFAULT_MAX_CONST_ELEMS = 256


def const_arrays(closed_jaxpr) -> list[tuple[tuple[int, ...], str, int]]:
    """Every array constant baked into a closed jaxpr, as
    ``(shape, dtype, elems)`` — scalars report as ``((), dtype, 1)``."""
    out = []
    for c in getattr(closed_jaxpr, "consts", ()) or ():
        shape = tuple(getattr(c, "shape", ()) or ())
        elems = math.prod(shape) if shape else 1
        out.append((shape, str(getattr(c, "dtype", type(c).__name__)),
                    elems))
    return out


def lint_baked_constants(
    fn_or_jaxpr,
    *args,
    max_elems: int = DEFAULT_MAX_CONST_ELEMS,
    program: str = "<fn>",
) -> list[Violation]:
    """Flag closure-captured array constants above ``max_elems``.

    Accepts a closed jaxpr directly, or a callable + example/abstract
    args (traced via ``jax.make_jaxpr`` — no compile, no execution).
    """
    if hasattr(fn_or_jaxpr, "consts"):
        closed = fn_or_jaxpr
    else:
        import jax

        fn = fn_or_jaxpr
        if hasattr(fn, "trace"):  # a jitted callable
            closed = fn.trace(*args).jaxpr
        else:
            closed = jax.make_jaxpr(fn)(*args)
    out: list[Violation] = []
    for shape, dtype, elems in const_arrays(closed):
        if elems > max_elems:
            out.append(Violation(
                program=program,
                rule="baked-constant",
                message=(
                    f"jaxpr bakes in a {list(shape)} {dtype} constant "
                    f"({elems} elems > bound {max_elems}) — closure-"
                    "captured arrays recompile on every value change "
                    "and poison CompileCache keys; pass it as an "
                    "operand instead"
                ),
                location=f"const dtype={dtype}",
            ))
    return out
