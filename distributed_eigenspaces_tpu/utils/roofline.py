"""FLOP accounting + measured matmul anchor — makes the perf claims
auditable (round-2 verdict: "samples/s only" is not checkable without
re-deriving the arithmetic).

Two halves:

- :func:`step_flop_model` — analytic FLOP counts per online step for the
  subspace-solver trainers, split into the cold first step (Gram build +
  full iteration count) and the warm steady state (streaming ``X^T (X v)``
  passes at ``warm_start_iters``). The model counts the dominant matmul
  terms only (MAC = 2 FLOPs); orthonormalization, the (m*k)-sized merge
  eigh and the state fold are O(d*k^2 + (m*k)^3) — <1% at every BASELINE
  config — and are deliberately excluded so the model is simple enough to
  check by hand.
- :func:`measure_matmul_anchor` — the achievable-matmul-rate denominator,
  measured the same way the benchmark measures the trainer (one chained
  program, salted operands, value-fetch fence — BASELINE.md "Timing
  methodology"). Roofline percentages against a *measured* anchor stay
  honest across hosts: on the axon dev tunnel the same code reports the
  tunnel-degraded anchor, on a real v5e host the MXU one.

The reference has no analogue (it publishes no numbers at all, SURVEY.md
§6); this is the framework's own auditability obligation.
"""

from __future__ import annotations

import time


def step_flop_model(
    m: int,
    n: int,
    d: int,
    k: int,
    cold_iters: int,
    warm_iters: int | None,
) -> dict:
    """Dominant-term FLOPs per online step for the subspace trainers.

    Both phases follow ``_local_eigenspaces``'s ACTUAL route dispatch
    (``worker_pool.py``): a solve streams (``iters * 4 n d k`` — two
    tall-skinny passes per iteration) when ``d >= 4096`` or
    ``2 k iters < d and iters <= 6``; otherwise it takes the Gram route
    (``2 n d^2`` + ``iters`` matvecs ``2 d^2 k``). Warm steps use the
    same rule at ``warm_iters`` — small-d/large-k configs (e.g. 768-d
    top-256) Gram even when warm, and a streaming-only warm formula
    would overcount their rate by ~``d / (2 k iters)``.

    Returns ``{"cold_flops_per_step", "warm_flops_per_step"}``; the warm
    entry equals the cold one when warm starts are off (every step runs
    the full count).
    """

    def per_step(iters: int) -> int:
        streams = d >= 4096 or (2 * k * iters < d and iters <= 6)
        if streams:
            return m * iters * 4 * n * d * k
        return m * (2 * n * d * d + iters * 2 * d * d * k)

    cold = per_step(cold_iters)
    warm = cold if warm_iters is None else per_step(warm_iters)
    return {"cold_flops_per_step": cold, "warm_flops_per_step": warm}


def fit_total_flops(model: dict, steps: int) -> int:
    """Model FLOPs of a whole fit: one cold step + (steps-1) warm steps."""
    return model["cold_flops_per_step"] + max(steps - 1, 0) * model[
        "warm_flops_per_step"
    ]


def measure_matmul_anchor(size: int = 2048, chain: int = 100) -> float:
    """Measured achievable bf16 matmul rate (TF/s) on the current default
    device: ``chain`` dependent ``size^3`` matmuls as ONE program, timed
    with a value-fetch fence on fresh operands (the tunneled dev backend
    neither fences on ``block_until_ready`` nor re-executes cached
    (executable, operands) pairs — BASELINE.md).

    The chain is dependent (each matmul consumes the previous result) so
    XLA cannot elide or batch it; renormalizing by the max element each
    link keeps bf16 from overflowing to inf over hundreds of links.
    """
    import jax
    import jax.numpy as jnp

    def chained(a, b):
        def body(x, _):
            y = jnp.matmul(a, x, preferred_element_type=jnp.float32)
            y = y / jnp.maximum(jnp.max(jnp.abs(y)), 1e-30)
            return y.astype(jnp.bfloat16), None
        out, _ = jax.lax.scan(body, b, None, length=chain)
        return out

    f = jax.jit(chained)
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (size, size), jnp.bfloat16)
    b = jax.random.normal(jax.random.PRNGKey(1), (size, size), jnp.bfloat16)
    float(jnp.sum(f(a, b).astype(jnp.float32)))  # compile + warm
    # fixed dispatch+fetch cost (~100 ms over the axon tunnel): measured
    # on a trivial program with fresh operands and subtracted (capped at
    # half the raw time), else the anchor under-reports the chip by the
    # RPC/chain-time ratio
    tiny = jax.jit(lambda x: x + 1.0)
    s = tiny(jnp.zeros(()))
    float(s)
    t0 = time.perf_counter()
    for i in range(3):
        s = tiny(s + 1.0)
        float(s)
    rpc = (time.perf_counter() - t0) / 3
    a2 = a + jnp.bfloat16(1e-3)  # fresh operands: defeat result caching
    t0 = time.perf_counter()
    float(jnp.sum(f(a2, b).astype(jnp.float32)))
    dt_raw = time.perf_counter() - t0
    dt = dt_raw - min(rpc, 0.5 * dt_raw)
    return (chain * 2 * size**3) / dt / 1e12


def step_byte_model(
    m: int,
    n: int,
    d: int,
    k: int,
    cold_iters: int,
    warm_iters: int | None,
    itemsize: int = 2,
    state: str = "dense",
) -> dict:
    """Dominant-term HBM bytes per online step for the subspace trainers,
    following the SAME route dispatch as :func:`step_flop_model` (and the
    actual solver, ``worker_pool.py``). Round 5 completed the model
    (verdict item 5 — the old X-reads-only version was a known
    undercount, which made ``pct_of_hbm_anchor`` quietly low):

    streaming route, per solver iteration:
      - X passes: the (m, n, d) block read TWICE (``X^T (X v)``),
        ``itemsize`` = the STAGED dtype (int8 staging halves this, the
        binding term);
      - the (m, n, k) ``Xv`` intermediate: one fp32 write + one read;
      - basis traffic: ~4 fp32 passes over (m, d, k) (matvec read +
        result write, orthonormalization read + write; the k x k
        Grams/Cholesky are O(k^2) — excluded).
    per step: the factor merge (~2 fp32 passes over (m, d, k)) and the
    state fold — ``state="dense"``: sigma_tilde read + write (2 d^2
    fp32, the dense scan/segmented trainers); ``state="lowrank"``: ~2
    passes over the rank-r carry (~(k+16)-wide — the feature-sharded /
    sketch trainers, where no d x d exists by design).

    Gram route: block read once + d x d Gram write (fp32, per worker) +
    one Gram read per matvec iteration + the same merge/fold terms.

    The byte twin of :func:`step_flop_model`, and the machine-readable
    reason an HBM-bound config cannot approach the FLOP anchor: its
    ceiling is the measured HBM rate instead.
    """
    block = m * n * d * itemsize
    merge = 2 * m * d * k * 4
    if state == "lowrank":
        fold = 2 * d * (k + 16) * 4
    else:
        fold = 2 * d * d * 4

    def per_step(iters: int) -> int:
        streams = d >= 4096 or (2 * k * iters < d and iters <= 6)
        if streams:
            per_iter = (
                block * 2          # the two tall-skinny X passes
                + 2 * m * n * k * 4  # Xv intermediate write + read
                + 4 * m * d * k * 4  # basis passes (matvec + orth)
            )
            return per_iter * iters + merge + fold
        return (
            block
            + m * (1 + iters) * d * d * 4  # Gram write + per-iter reads
            + merge + fold
        )

    return {
        "cold_bytes_per_step": per_step(cold_iters),
        "warm_bytes_per_step": (
            per_step(warm_iters) if warm_iters is not None
            else per_step(cold_iters)
        ),
    }


def _hbm_timed_factory(mb: int):
    """One ``timed(count)`` closure for an ``mb``-MB add-chain probe —
    best-of-3 fenced runs of a ``count``-link dependent whole-array add
    program on fresh operands."""
    import jax
    import jax.numpy as jnp

    n = mb * (1 << 20) // 4
    x = jnp.zeros((n,), jnp.float32)

    def make(count):
        def f(x0):
            def body(acc, _):
                return acc + 1.0, None

            out, _ = jax.lax.scan(body, x0, None, length=count)
            return out

        return jax.jit(f)

    def timed(count):
        f = make(count)
        float(jnp.sum(f(x)[:2]))  # compile + warm
        best = float("inf")
        for s in (1.0, 2.0, 3.0):  # fresh operands: defeat result caching
            t0 = time.perf_counter()
            float(jnp.sum(f(x + s)[:2]))
            best = min(best, time.perf_counter() - t0)
        return best

    return timed


def measure_hbm_anchor_probe(
    sizes_mb: list[int] | None = None, base: int | None = None,
    ratio: int = 2, small: bool = False,
) -> dict:
    """The HBM-anchor probe with RETRY and a structured record (round-6
    satellite: a bare ``hbm_probe_failed: true`` was undiagnosable —
    BENCH_r05 shipped without a bandwidth verdict and nothing said why).

    Tries the consistency-checked differenced measurement at 2-3 buffer
    sizes (a jittery session often fails at one size and passes at
    another — smaller buffers run shorter programs with less exposure
    to the jitter window) and returns::

        {"gb_per_sec": float | None,      # None = every size failed
         "attempts": [{"mb", "chain_lengths", "seconds",
                       "est1_per_link_s", "est2_per_link_s",
                       "failed_check"?}, ...],
         "failed_check": str}             # only when gb_per_sec is None

    ``attempts`` carries the raw timings of every size tried, so a
    persistent failure in a recorded report is diagnosable (WHICH
    consistency check failed, against WHAT numbers) instead of a bare
    boolean. ``small=True`` is the ONE definition of the CI-shrunk
    preset (shared by bench.py and evals.py so their anchors stay
    comparable)."""
    if sizes_mb is None:
        sizes_mb = [32, 16, 8] if small else [256, 128, 64]
    if base is None:
        base = 6 if small else 24
    attempts: list[dict] = []
    for mb in sizes_mb:
        dt, diag = _consistent_marginal_diag(
            _hbm_timed_factory(mb), base, ratio
        )
        attempts.append({"mb": mb, **diag})
        if dt == dt and dt > 0:
            return {
                "gb_per_sec": 2 * mb * (1 << 20) / dt / 1e9,
                "attempts": attempts,
            }
    return {
        "gb_per_sec": None,
        "attempts": attempts,
        "failed_check": attempts[-1].get("failed_check", "unknown"),
    }


def measure_hbm_anchor(
    mb: int | None = None, base: int | None = None, ratio: int = 2,
    small: bool = False,
) -> float:
    """Measured achievable HBM streaming rate (GB/s, read+write counted):
    a dependent chain of whole-array adds over an fp32 buffer, two chain
    lengths differenced so dispatch/launch/fence cancel — the bandwidth
    twin of :func:`measure_matmul_anchor`. Each link reads and writes
    the buffer once: 2 * mb MB of traffic per link. Retries 2-3 buffer
    sizes before giving up (see :func:`measure_hbm_anchor_probe`, which
    also returns the structured attempt record); NaN = every size
    failed this session."""
    out = measure_hbm_anchor_probe(
        sizes_mb=None if mb is None else [mb], base=base, ratio=ratio,
        small=small,
    )
    return float("nan") if out["gb_per_sec"] is None else out["gb_per_sec"]


def _consistent_marginal_diag(timed, base: int, ratio: int):
    """Differenced per-unit time from THREE chain lengths, accepted only
    when the two independent estimates agree within 2x — a single
    differenced pair on a jittery tunnel can silently produce a
    wildly-wrong number (observed: an HBM "anchor" 3x below the same
    chip's earlier sessions, an op latency 30x below), and a wrong
    denominator poisons every percentage derived from it. Returns
    ``(value_or_nan, diag)`` — the diag dict records the chain lengths,
    raw seconds and both estimates, plus ``failed_check`` naming the
    rejection, so callers can report a FAILURE as evidence instead of a
    bare boolean (round-6 satellite)."""
    t1 = timed(base)
    t2 = timed(base * ratio)
    t3 = timed(base * (2 * ratio - 1))
    per = base * (ratio - 1)
    est1 = (t2 - t1) / per
    est2 = (t3 - t2) / per
    diag = {
        "chain_lengths": [base, base * ratio, base * (2 * ratio - 1)],
        "seconds": [round(t1, 6), round(t2, 6), round(t3, 6)],
        "est1_per_link_s": round(est1, 9),
        "est2_per_link_s": round(est2, 9),
    }
    if est1 <= 0 or est2 <= 0:
        diag["failed_check"] = "nonpositive_marginal"
        return float("nan"), diag
    if max(est1, est2) > 2.0 * min(est1, est2):
        diag["failed_check"] = "estimates_disagree_2x"
        return float("nan"), diag
    return 0.5 * (est1 + est2), diag


def _consistent_marginal(timed, base: int, ratio: int) -> float:
    """Value-only wrapper of :func:`_consistent_marginal_diag` (kept for
    callers that don't report diagnostics)."""
    return _consistent_marginal_diag(timed, base, ratio)[0]


def roofline_fields(
    model: dict,
    *,
    steps: int,
    fit_seconds: float,
    warm_seconds_per_step: float | None = None,
    cold_seconds: float | None = None,
    anchor_tflops: float | None = None,
    byte_model: dict | None = None,
    hbm_anchor_gbps: float | None = None,
    hbm_probe_record: dict | None = None,
) -> dict:
    """Assemble the JSON roofline block from a flop model + measured times.

    ``warm_seconds_per_step`` should be a *marginal* time (two fit lengths
    differenced) so dispatch and the cold step cancel; when given, the
    warm-phase achieved TF/s and percent-of-anchor are emitted. All rates
    derive from MODEL flops — stated dominant-term counts, not hardware
    counters.

    ``byte_model`` + ``hbm_anchor_gbps`` (:func:`step_byte_model` /
    :func:`measure_hbm_anchor`) add the BANDWIDTH roofline: achieved
    GB/s against the measured HBM rate, plus ``bound`` — the
    machine-reported reason a config sits where it does (round-3 verdict
    item 1): "hbm" / "mxu" when the achieved fraction of that anchor
    exceeds half the roof, else "latency" (neither resource near its
    roof: the time goes to sequential small-op chains / dispatch — the
    regime the warm-start and sketch designs attack)."""
    total = fit_total_flops(model, steps)
    out = {
        "cold_flops_per_step": int(model["cold_flops_per_step"]),
        "warm_flops_per_step": int(model["warm_flops_per_step"]),
        "model_flops_total": int(total),
        "achieved_tflops": round(total / fit_seconds / 1e12, 4),
    }
    if anchor_tflops is not None:
        out["anchor_tflops"] = round(anchor_tflops, 4)
        out["pct_of_anchor"] = round(
            100.0 * (total / fit_seconds / 1e12) / anchor_tflops, 2
        )
    if byte_model is not None:
        bytes_total = byte_model["cold_bytes_per_step"] + max(
            steps - 1, 0
        ) * byte_model["warm_bytes_per_step"]
        gbps = bytes_total / fit_seconds / 1e9
        out["model_bytes_total"] = int(bytes_total)
        out["achieved_gb_per_sec"] = round(gbps, 1)
        if hbm_anchor_gbps is not None and hbm_anchor_gbps != hbm_anchor_gbps:
            # NaN = the probe's consistency check rejected this session's
            # estimates at EVERY retried buffer size — say so instead of
            # silently omitting the block (consumers must be able to tell
            # "not HBM-bound" from "anchor never measured"), and attach
            # the structured attempt record so the failure is diagnosable
            # (which check failed, against what raw timings) rather than
            # a bare boolean (round-6 satellite; BENCH_r05 shipped
            # "hbm_probe_failed": true with no evidence)
            out["hbm_probe_failed"] = True
            if hbm_probe_record is not None:
                out["hbm_probe"] = {
                    "failed_check": hbm_probe_record.get(
                        "failed_check", "unknown"
                    ),
                    "attempts": hbm_probe_record.get("attempts", []),
                }
        if hbm_anchor_gbps is not None and hbm_anchor_gbps == hbm_anchor_gbps:
            out["hbm_anchor_gb_per_sec"] = round(hbm_anchor_gbps, 1)
            out["pct_of_hbm_anchor"] = round(
                100.0 * gbps / hbm_anchor_gbps, 2
            )
            if out["pct_of_hbm_anchor"] > 110:
                # modeled traffic cannot exceed the physical rate: the
                # anchor under-measured this session (or the byte model
                # overcounts) — say so next to the number
                out["hbm_anchor_suspect"] = True
            if "pct_of_anchor" in out:
                hbm_pct, flop_pct = (
                    out["pct_of_hbm_anchor"], out["pct_of_anchor"],
                )
                if hbm_pct >= 50 and hbm_pct >= flop_pct:
                    out["bound"] = "hbm"
                elif flop_pct >= 50:
                    out["bound"] = "mxu"
                else:
                    out["bound"] = "latency"
    if warm_seconds_per_step is not None and warm_seconds_per_step > 0:
        warm_tf = model["warm_flops_per_step"] / warm_seconds_per_step / 1e12
        out["warm_ms_per_step"] = round(warm_seconds_per_step * 1e3, 4)
        out["warm_tflops"] = round(warm_tf, 3)
        if anchor_tflops is not None:
            out["warm_pct_of_anchor"] = round(100.0 * warm_tf / anchor_tflops, 2)
    if cold_seconds is not None and cold_seconds > 0:
        cold_tf = model["cold_flops_per_step"] / cold_seconds / 1e12
        out["cold_ms"] = round(cold_seconds * 1e3, 2)
        out["cold_tflops"] = round(cold_tf, 3)
        if anchor_tflops is not None:
            out["cold_pct_of_anchor"] = round(100.0 * cold_tf / anchor_tflops, 2)
    return out
