"""Fault injection (SURVEY.md §5.3).

The reference's failure story: a slave acks only after replying
(``distributed.py:53``), so AMQP redelivers a crashed worker's batch —
at-least-once, with no timeout, liveness, or master redundancy. The
TPU-native equivalent of "kill a slave process" is a worker mask: a dropped
worker's projector is excluded from the merge and the mean reweights over
survivors exactly (see ``WorkerPool.round(worker_mask=...)``).

This module generates deterministic fault schedules for tests and chaos
runs: per-step worker-drop masks (:class:`FaultInjector`), and — for the
supervised runs of ``runtime/supervisor.py`` — scheduled DATA corruption
(:class:`ChaosPlan` / :class:`ChaosStream`): NaN blocks, zeroed blocks,
transient stream exceptions, and a hard kill at a chosen step. The
supervisor's detection loops are exercised end to end by
``scripts/chaos.py`` and tests/test_supervisor.py.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np


class KillSwitch(RuntimeError):
    """Simulated hard process death (chaos harness kill-at-step-t).

    Deliberately NOT in the supervisor's retryable set: a real SIGKILL
    doesn't retry — it takes the process down, and recovery is the next
    process restoring the newest committed checkpoint and seeking the
    stream cursor. Tests/scripts catch it OUTSIDE ``supervised_fit`` and
    call ``supervised_fit`` again to simulate the restart.
    """


class FaultInjector:
    """Deterministic per-step worker-failure masks.

    ``drop_prob`` is the independent per-worker failure probability per
    step; at least one worker always survives (an all-dead round would make
    the merge undefined — the masked mean guards with max(count, 1) but the
    algorithm should see >= 1 contribution).

    Iterate it alongside the stream and pass to ``worker_masks=``::

        faults = FaultInjector(num_workers=8, drop_prob=0.2, seed=3)
        online_distributed_pca(stream, cfg, worker_masks=iter(faults))
    """

    def __init__(self, num_workers: int, drop_prob: float, seed: int = 0):
        if not 0.0 <= drop_prob < 1.0:
            raise ValueError(f"drop_prob must be in [0, 1), got {drop_prob}")
        self.num_workers = num_workers
        self.drop_prob = drop_prob
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_mask()

    def next_mask(self) -> np.ndarray:
        mask = (
            self._rng.random(self.num_workers) >= self.drop_prob
        ).astype(np.float32)
        if mask.sum() == 0:  # resurrect one survivor
            mask[self._rng.integers(self.num_workers)] = 1.0
        return mask


def kill_workers(num_workers: int, dead: list[int]) -> np.ndarray:
    """Explicit mask with the listed worker indices dead (scenario tests)."""
    mask = np.ones(num_workers, np.float32)
    for i in dead:
        mask[i] = 0.0
    if mask.sum() == 0:
        raise ValueError("cannot kill every worker")
    return mask


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """Deterministic corruption schedule for a block stream (1-based
    steps, matching the online loop's step numbering).

    ``nan_blocks`` / ``zero_blocks``: ``{step: [worker indices]}`` —
    the listed workers' row-blocks are overwritten with NaN / zeros
    before the block is yielded (the corrupt-input classes the
    supervisor's quarantine must catch: NaN is loud corruption, zeros
    model a reader that delivered an unwritten buffer).
    ``raise_at``: ``{step: message}`` — ``next()`` raises ``OSError``
    ONCE for that step, then delivers the step's block on the retry
    (the transient-IO class the supervisor's backoff absorbs).
    ``kill_at``: raise :class:`KillSwitch` INSTEAD of yielding this step
    — the hard-death class; fires once, so a restarted run streaming
    from its checkpoint cursor sails past.
    """

    nan_blocks: dict[int, list[int]] = dataclasses.field(
        default_factory=dict
    )
    zero_blocks: dict[int, list[int]] = dataclasses.field(
        default_factory=dict
    )
    raise_at: dict[int, str] = dataclasses.field(default_factory=dict)
    kill_at: int | None = None


@dataclasses.dataclass(frozen=True)
class ChurnPlan:
    """Deterministic membership-churn schedule for the FIT tier
    (ISSUE 8), consumed by ``runtime/membership.py ElasticStream``
    (1-based absolute steps, resume-safe like :class:`ChaosPlan`).

    ``kill_at``: ``{step: [slots]}`` — the listed workers CRASH before
    that round: their heartbeats stop and the membership table finds
    out via lease expiry (suspect after ``heartbeat_timeout_ms``, dead
    one grace later) — the liveness-detection path under test.
    ``leave_at``: graceful departures — the slot goes dead immediately
    (the worker said goodbye; no detection lag).
    ``rejoin_at``: the listed workers come back: they re-claim their
    old slot (``MembershipTable.join``) and are admitted at the NEXT
    round with a fresh lease — flapping is kills and rejoins
    interleaved on the same slot.
    ``straggle``: ``{step: {slot: delay_s}}`` — one-off delivery
    delays past the round start; a delay beyond
    ``cfg.round_deadline_ms`` misses the round and the rows fold into
    the NEXT merge.
    ``slow``: ``{slot: delay_s}`` — persistent stragglers (the delay
    applies every round; beyond the deadline this is a steady
    one-round lag, never a stall).
    """

    kill_at: dict[int, list[int]] = dataclasses.field(
        default_factory=dict
    )
    leave_at: dict[int, list[int]] = dataclasses.field(
        default_factory=dict
    )
    rejoin_at: dict[int, list[int]] = dataclasses.field(
        default_factory=dict
    )
    straggle: dict[int, dict[int, float]] = dataclasses.field(
        default_factory=dict
    )
    slow: dict[int, float] = dataclasses.field(default_factory=dict)

    def delay(self, step: int, slot: int) -> float:
        """Delivery delay (seconds past round start) for ``slot`` at
        ``step``: the scheduled one-off wins over the persistent
        rate."""
        d = self.straggle.get(step, {}).get(slot)
        if d is not None:
            return float(d)
        return float(self.slow.get(slot, 0.0))


@dataclasses.dataclass(frozen=True)
class ClientChaosPlan:
    """Deterministic population-chaos schedule for the SAMPLED-COHORT
    ingest tier (ISSUE 16), consumed by ``runtime/population.py``
    (1-based absolute rounds, resume-safe like :class:`ChaosPlan`).

    Client ROLES are assigned by population id range (deterministic,
    seed-independent): ids ``[0, P·nan_frac)`` are NaN submitters, the
    next ``P·poison_frac`` are colluding poisoners, the next
    ``P·straggler_frac`` are persistent stragglers; everyone else is
    honest. Uniform cohort sampling makes contiguous ranges equivalent
    to any other deterministic assignment.

    ``dropout_frac``: baseline i.i.d. per-sampled-client dropout
    probability per round — a dropped client contributes NOTHING (the
    participation-fraction deadline absorbs it; no detection lag, no
    placeholder).
    ``dropout_waves``: ``{round: frac}`` — rounds where the dropout
    probability SPIKES (a correlated outage wave). A wave deep enough
    to push arrivals below ``cfg.min_participation_frac`` triggers the
    participation-collapse arc (bounded wait → resume) under test.
    ``straggler_frac``: fraction of the population that is persistently
    SLOW: their contributions always miss the round deadline and fold
    one-step-stale into the NEXT round (the PR 2/PR 12 rule) — a
    steady one-round lag, never a stall.
    ``nan_frac``: fraction of the population whose submissions are NaN
    — the loud-corruption class the gauntlet's non-finite screen must
    quarantine with client id + reason.
    ``poison_frac``: fraction of the population that is Byzantine and
    COLLUDING: every poisoner submits the SAME sign-flipped adversarial
    basis (orthogonal to the planted one), scaled by ``poison_scale``.
    ``poison_scale``: norm multiplier on poison submissions. ``> 1``
    breaks near-orthonormality, so the gauntlet rejects it at the door
    (the attribution path); ``== 1`` stays exactly orthonormal and
    slips the gauntlet, so the norm-clipped trimmed mean + affinity
    screen must stop the steering (the robust-statistics path). The
    bench runs both.
    """

    dropout_frac: float = 0.0
    dropout_waves: dict[int, float] = dataclasses.field(
        default_factory=dict
    )
    straggler_frac: float = 0.0
    nan_frac: float = 0.0
    poison_frac: float = 0.0
    poison_scale: float = 1.0

    def __post_init__(self):
        for name in ("dropout_frac", "straggler_frac", "nan_frac",
                     "poison_frac"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(
                    f"{name} must be a fraction in [0, 1], got {v!r}"
                )
        for rnd, frac in self.dropout_waves.items():
            if not 0.0 <= frac <= 1.0:
                raise ValueError(
                    f"dropout_waves[{rnd}] must be a fraction in "
                    f"[0, 1], got {frac!r}"
                )

    def dropout_at(self, rnd: int) -> float:
        """Effective dropout probability for round ``rnd``: a scheduled
        wave overrides the baseline (one-off wins over persistent — the
        :class:`ChurnPlan.delay` rule)."""
        return float(self.dropout_waves.get(rnd, self.dropout_frac))


@dataclasses.dataclass
class ServeChaosPlan:
    """Deterministic fault schedule for the SERVE tier (ISSUE 7 — the
    read-path dual of :class:`ChaosPlan`), consumed by
    :class:`ServeChaosHook` wired into ``QueryServer(fault_hook=...)``.

    ``kill_lane_at_batch``: the Nth dispatched bucket raises
    :class:`KillSwitch` — a hard serve-lane death (the lane thread
    exits without failing its bucket, exactly like a killed thread; the
    watchdog restarts the lane and lease expiry re-queues the bucket).
    Fires ONCE, so the restarted lane sails past — the restart IS the
    recovery under test.
    ``fail_signatures``: admission signatures whose every dispatch
    raises ``OSError`` — the poisoned-signature class the per-signature
    circuit breaker must isolate.
    ``fail_error``: the poisoned dispatch's message.
    """

    kill_lane_at_batch: int | None = None
    fail_signatures: tuple = ()
    fail_error: str = "chaos: poisoned dispatch"


class ServeChaosHook:
    """Stateful dispatch-time injector for a :class:`ServeChaosPlan`.
    Counts dispatched buckets; thread-safe (dispatch lanes may be
    concurrent)."""

    def __init__(self, plan: ServeChaosPlan):
        import threading

        self.plan = plan
        self.batches = 0
        self.killed = False
        self._lock = threading.Lock()

    def __call__(self, bucket) -> None:
        with self._lock:
            self.batches += 1
            n = self.batches
            kill = (
                self.plan.kill_lane_at_batch is not None
                and n >= self.plan.kill_lane_at_batch
                and not self.killed
            )
            if kill:
                self.killed = True
        if kill:
            raise KillSwitch(f"chaos: serve lane killed at batch {n}")
        if bucket.signature in tuple(self.plan.fail_signatures):
            raise OSError(self.plan.fail_error)


def corrupt_version_file(version_dir: str, *, offset: int = -8,
                         flip: int = 0xFF) -> str:
    """Flip one byte of a committed registry version's payload
    (``basis.npz``) IN PLACE, leaving its commit marker intact — the
    checksum-mismatch fault class registry recovery must quarantine
    (disk rot / tamper, as opposed to the torn-snapshot class a killed
    publisher leaves). Returns the corrupted payload path."""
    import os

    path = os.path.join(version_dir, "basis.npz")
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        pos = f.tell()
        b = f.read(1)
        f.seek(pos)
        f.write(bytes([b[0] ^ flip]))
    return path


class ChaosStream:
    """Apply a :class:`ChaosPlan` to a block stream.

    An ITERATOR class, not a generator: a generator that raises is dead
    (``next()`` after an exception is ``StopIteration``), but transient
    faults must leave the stream resumable — the supervisor retries the
    SAME pull and gets the step's block. ``first_step`` offsets the step
    numbering for resumed streams (a run restored at step t sees its
    first block as step t+1, so the plan keys stay absolute).
    """

    def __init__(self, stream, plan: ChaosPlan, *, first_step: int = 1):
        self._it = iter(stream)
        self._plan = plan
        self._step = first_step - 1
        self._raised: set[int] = set()
        self._killed = False

    def __iter__(self) -> "ChaosStream":
        return self

    def __next__(self):
        t = self._step + 1
        if self._plan.kill_at == t and not self._killed:
            self._killed = True
            raise KillSwitch(f"chaos kill at step {t}")
        if t in self._plan.raise_at and t not in self._raised:
            self._raised.add(t)
            raise OSError(self._plan.raise_at[t])
        block = next(self._it)
        self._step = t
        bad = self._plan.nan_blocks.get(t), self._plan.zero_blocks.get(t)
        if bad != (None, None):
            block = np.array(block, np.float32, copy=True)
            for workers, value in zip(bad, (np.nan, 0.0)):
                for w in workers or ():
                    block[w] = value
        return block
