"""Fault injection (SURVEY.md §5.3).

The reference's failure story: a slave acks only after replying
(``distributed.py:53``), so AMQP redelivers a crashed worker's batch —
at-least-once, with no timeout, liveness, or master redundancy. The
TPU-native equivalent of "kill a slave process" is a worker mask: a dropped
worker's projector is excluded from the merge and the mean reweights over
survivors exactly (see ``WorkerPool.round(worker_mask=...)``).

This module generates deterministic fault schedules for tests and chaos
runs.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


class FaultInjector:
    """Deterministic per-step worker-failure masks.

    ``drop_prob`` is the independent per-worker failure probability per
    step; at least one worker always survives (an all-dead round would make
    the merge undefined — the masked mean guards with max(count, 1) but the
    algorithm should see >= 1 contribution).

    Iterate it alongside the stream and pass to ``worker_masks=``::

        faults = FaultInjector(num_workers=8, drop_prob=0.2, seed=3)
        online_distributed_pca(stream, cfg, worker_masks=iter(faults))
    """

    def __init__(self, num_workers: int, drop_prob: float, seed: int = 0):
        if not 0.0 <= drop_prob < 1.0:
            raise ValueError(f"drop_prob must be in [0, 1), got {drop_prob}")
        self.num_workers = num_workers
        self.drop_prob = drop_prob
        self._rng = np.random.default_rng(seed)

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.next_mask()

    def next_mask(self) -> np.ndarray:
        mask = (
            self._rng.random(self.num_workers) >= self.drop_prob
        ).astype(np.float32)
        if mask.sum() == 0:  # resurrect one survivor
            mask[self._rng.integers(self.num_workers)] = 1.0
        return mask


def kill_workers(num_workers: int, dead: list[int]) -> np.ndarray:
    """Explicit mask with the listed worker indices dead (scenario tests)."""
    mask = np.ones(num_workers, np.float32)
    for i in dead:
        mask[i] = 0.0
    if mask.sum() == 0:
        raise ValueError("cannot kill every worker")
    return mask
