"""Structured metrics & logging (SURVEY.md §5.5).

Replaces the reference's observability — a dozen ``print`` calls
(``distributed.py:35,44,56,92,98,107,114,120,131,137,142``) and one
wall-clock span (``distributed.py:93,131``) — with per-step structured
records: throughput (the BASELINE.json samples/sec metric), step latency,
and optional accuracy (principal angle vs a reference subspace).

Since ISSUE 6 this is also the aggregation half of the unified
telemetry layer (``utils/telemetry.py``):

- every event list is a bounded :class:`~.telemetry.RingLog` — evicted
  entries fold into running aggregates (counters + mergeable
  log-bucket :class:`~.telemetry.Histogram`\\ s), so a long-lived
  server never grows without limit and ``summary()`` stays correct
  after eviction;
- every event carries BOTH clocks: ``t_mono`` (``time.perf_counter``,
  orders and subtracts correctly) and ``t_unix`` (``time.time``,
  correlates across processes) — the pre-ISSUE-6 mix of one or the
  other made merged JSON streams unsortable;
- ``summary()["serving"]`` decomposes request latency into
  queue_wait / compile_stall / compute / other per percentile, and
  ``summary()["slo"]`` reports rolling-window attainment +
  error-budget burn against declared p99 targets
  (``cfg.serve_slo_p99_ms`` / ``cfg.fleet_slo_p99_ms``);
- an attached :class:`~.telemetry.Tracer` (:meth:`attach_tracer`)
  receives per-step spans and is handed to the compile cache, so the
  exported Chrome-trace timeline covers fit, serve, fleet, drift and
  compile events together.
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO

from distributed_eigenspaces_tpu.utils.telemetry import (
    Histogram,
    RingLog,
    slo_summary,
    tracer_of,
)

#: default ring-buffer retention per event list (overridable per logger
#: and via ``PCAConfig.metrics_retention``)
DEFAULT_RETENTION = 4096

#: decomposition component keys, in report order: per-request latency =
#: queue_wait + compile_stall + compute + other (pre/post dispatch
#: overhead), all in seconds
DECOMP_KEYS = ("queue_wait_s", "compile_stall_s", "compute_s", "other_s")


def _stamp(rec: dict) -> dict:
    """Both clocks on every event (ISSUE 6 satellite): ``t_mono`` for
    ordering/durations, ``t_unix`` for cross-process correlation.
    ``t`` stays the monotonic stamp for existing consumers."""
    now_mono = time.perf_counter()
    rec.setdefault("t_mono", now_mono)
    rec.setdefault("t_unix", time.time())
    rec.setdefault("t", rec["t_mono"])
    return rec


class MetricsLogger:
    """Collects per-step metrics; optionally streams them as JSON lines.

    Use as an ``on_step`` callback factory::

        metrics = MetricsLogger(samples_per_step=m * n)
        online_distributed_pca(stream, cfg, on_step=metrics.on_step)
        print(metrics.summary())
    """

    def __init__(
        self,
        *,
        samples_per_step: int = 0,
        stream: IO | None = None,
        reference_subspace=None,
        retention: int = DEFAULT_RETENTION,
        slo_p99_ms: float | None = None,
        fleet_slo_p99_ms: float | None = None,
        tracer=None,
    ):
        self.samples_per_step = samples_per_step
        self.stream = stream
        self.reference_subspace = reference_subspace
        self.retention = retention
        #: declared serving SLO target (p99 request latency, ms) —
        #: ``summary()["slo"]["serve"]`` reports attainment against it
        self.slo_p99_ms = slo_p99_ms
        #: the fleet equivalent (p99 fit-request latency, ms)
        self.fleet_slo_p99_ms = fleet_slo_p99_ms
        #: optional ``telemetry.Tracer`` — per-step spans and compile
        #: events land on its exported timeline (:meth:`attach_tracer`)
        self.tracer = tracer
        #: per-step records (ring buffer; evictions fold into running
        #: throughput aggregates so the summary survives long runs)
        self.records = RingLog(retention, self._evict_step)
        #: structured fault events (runtime/supervisor.py): quarantined
        #: workers, retried pulls/steps, resumes — the run's fault
        #: ledger, surfaced by :meth:`summary`
        self.fault_records = RingLog(retention, self._evict_fault)
        #: ingest-pipeline counters (runtime/prefetch.py PrefetchStats),
        #: attached via :meth:`attach_ingest` — surfaced by
        #: :meth:`summary` under "ingest"
        self.ingest_stats = None
        #: query-serving events (serving/server.py QueryServer batches,
        #: serving/drift.py DriftMonitor refreshes) — surfaced by
        #: :meth:`summary` under "serving"
        self.serve_records = RingLog(retention, self._evict_serve)
        #: fleet-serving events (parallel/fleet.py FleetServer bucket
        #: dispatches) — surfaced by :meth:`summary` under "fleet"
        self.fleet_records = RingLog(retention, self._evict_fleet)
        #: elastic-membership events (runtime/membership.py
        #: MembershipTable / ElasticStream): joins, leaves,
        #: suspect→dead transitions, deadline-closed rounds — surfaced
        #: by :meth:`summary` under "membership"
        self.membership_records = RingLog(
            retention, self._evict_membership
        )
        #: live membership table (attach_membership) — its snapshot
        #: (states, generations, quorum) rides the summary
        self.membership_table = None
        #: hierarchical-merge events (runtime/tiers.py TieredStream /
        #: TierSet): per-tier round closes, stale folds, tier quorum
        #: transitions — surfaced by :meth:`summary` under "merge"
        self.merge_records = RingLog(retention, self._evict_merge)
        #: registry-replication events (serving/replication.py
        #: ReplicaRegistry installs / staleness breaches / fenced
        #: zombie commits, PublisherLease failovers) — surfaced by
        #: :meth:`summary` under "replication"
        self.replication_records = RingLog(
            retention, self._evict_replication
        )
        #: population-ingest events (runtime/population.py
        #: PopulationIngest): cohort round closes, client quarantines
        #: by reason, participation collapses/restores, trimmed-merge
        #: stats — surfaced by :meth:`summary` under "population"
        self.population_records = RingLog(
            retention, self._evict_population
        )
        #: eigensolver convergence events (solvers/ deflation lanes and
        #: gap-adaptive subspace stops, ISSUE 18): per-solve
        #: ``iters_used`` / residuals, per-lane — surfaced by
        #: :meth:`summary` under "solver"
        self.solver_records = RingLog(retention, self._evict_solver)
        #: control-plane decisions (runtime/controller.py Controller,
        #: ISSUE 19): every autoscaler action / rollback / freeze with
        #: the lineage ``{trigger, knob, from, to, plan_id}`` and the
        #: telemetry evidence that triggered it — surfaced by
        #: :meth:`summary` under "controller"
        self.controller_records = RingLog(
            retention, self._evict_controller
        )
        #: compile-lifecycle counters (utils/compile_cache.py
        #: CompileCache), attached via :meth:`attach_compile` —
        #: surfaced by :meth:`summary` under "compile"
        self.compile_cache = None
        #: live read-path health sources (serving/server.py
        #: ``QueryServer.health``), attached via
        #: :meth:`attach_serve_health` — merged into
        #: ``summary()["serving"]["health"]``
        self.serve_health_sources: list = []
        #: static-analysis verdict (analysis/report.py) — a report
        #: dict or a zero-arg callable producing one, attached via
        #: :meth:`attach_analysis`; surfaced by :meth:`summary`
        #: under "analysis"
        self.analysis_report = None
        self._last_time = None
        self._fit_trace = None
        # evicted-entry aggregates: what the ring buffers folded away
        self._step_agg = {
            "steps": 0, "sps_sum": 0.0, "sps_n": 0, "sps_max": None,
        }
        self._fault_agg: dict = {"count": 0, "by_kind": {}}
        self._serve_agg = self._fresh_dispatch_agg()
        self._serve_agg["drifts"] = 0
        # read-path health eviction aggregates (ISSUE 7): sheds by
        # reason, lane restart/death counts, breaker transitions — so
        # summary()["serving"]["health"] covers the whole run even
        # after ring-buffer eviction
        self._serve_agg["sheds_by_reason"] = {}
        self._serve_agg["lane_restarts"] = 0
        self._serve_agg["lane_deaths"] = 0
        self._serve_agg["breaker_trips"] = 0
        self._fleet_agg = self._fresh_dispatch_agg()
        # elastic-membership eviction aggregates (ISSUE 8): event
        # counts by kind, round outcomes (deadline closes, stale
        # folds), and the per-round arrival histogram — so
        # summary()["membership"] covers the whole run after eviction
        self._membership_agg = {
            "count": 0, "by_kind": {}, "rounds": 0,
            "deadline_closed": 0, "stale_folds": 0,
            "arrival_hist": {},
        }
        # hierarchical-merge eviction aggregates (ISSUE 12): event
        # counts by kind plus PER-TIER round outcomes (fan-in,
        # deadline closes, stale folds, arrival histogram) — so
        # summary()["merge"] covers the whole run after eviction
        self._merge_agg: dict = {
            "count": 0, "by_kind": {}, "tiers": {}, "wire": {},
        }
        # registry-replication eviction aggregates (ISSUE 14): event
        # counts by kind, install/staleness/fencing/failover counters,
        # failover recovery times, and the mergeable propagation-lag
        # histogram — so summary()["replication"] (propagation p99,
        # failover count + recovery_ms) covers the whole run after
        # ring-buffer eviction
        self._replication_agg: dict = {
            "count": 0, "by_kind": {}, "installs": 0, "stale": 0,
            "fenced": 0, "failovers": 0, "recovery_ms": [],
            "lag_hist": Histogram(),
        }
        # population-ingest eviction aggregates (ISSUE 16): event
        # counts by kind, cohort-round outcomes (participation decile
        # histogram, one-step-stale folds), quarantines by rejection
        # reason, and the running trim-fraction mean — so
        # summary()["population"] covers the whole run after eviction
        self._population_agg: dict = {
            "count": 0, "by_kind": {}, "rounds": 0, "stale_folds": 0,
            "participation_hist": {}, "rejects_by_reason": {},
            "trim_frac_sum": 0.0, "trim_frac_n": 0,
        }
        # solver-convergence eviction aggregates (ISSUE 18): solve
        # counts by kind plus PER-LANE iteration totals (sum/max,
        # early-stop count) — so summary()["solver"] covers the whole
        # run after ring-buffer eviction
        self._solver_agg: dict = {
            "count": 0, "by_kind": {}, "by_lane": {},
        }
        # control-plane eviction aggregates (ISSUE 19): decision counts
        # by kind plus per-knob action/rollback counters — so
        # summary()["controller"] covers the whole run after eviction
        self._controller_agg: dict = {
            "count": 0, "by_kind": {}, "by_knob": {}, "rollbacks": 0,
        }

    @staticmethod
    def _fresh_dispatch_agg() -> dict:
        """Eviction aggregate shared by the serving and fleet sections:
        counters plus mergeable latency histograms (total + the
        decomposition components), so percentiles survive eviction."""
        return {
            "events": 0, "requests": 0, "rejected": 0, "swaps": 0,
            "occ_sum": 0.0, "occ_n": 0,
            # batch-occupancy waste ledger (ISSUE 17): padded rows per
            # signature bucket, mean fill fraction, and the
            # admit-to-dispatch wait histogram the continuous-batching
            # claim is judged by
            "padded_rows": 0, "padded_by_sig": {},
            # heterogeneous-k bucketing waste (ISSUE 18): eigenvector
            # lanes fitted only because a tenant's k was padded up to
            # the shared bucket width, attributed by signature
            "padded_lanes": 0, "padded_lanes_by_sig": {},
            "fill_sum": 0.0, "fill_n": 0,
            "compile_misses": 0, "compile_stall_ms": 0.0,
            "by_sig": {}, "t_min": None, "t_max": None,
            "versions": set(),
            "slo_requests": 0, "slo_violations": 0,
            "hist": {
                "total_s": Histogram(),
                "admit_to_dispatch_s": Histogram(),
                **{k: Histogram() for k in DECOMP_KEYS},
            },
        }

    def start(self) -> "MetricsLogger":
        self._last_time = time.perf_counter()
        return self

    def on_step(self, t: int, state, v_bar=None) -> None:
        now = time.perf_counter()
        rec: dict = {"step": int(t)}
        if self._last_time is not None:
            dt = now - self._last_time
            rec["step_seconds"] = round(dt, 6)
            if self.samples_per_step:
                rec["samples_per_sec"] = round(self.samples_per_step / dt, 1)
            tr = tracer_of(self)
            if self._fit_trace is None:
                self._fit_trace = tr.new_trace("fit")
            tr.record_span(
                "pca_step", self._last_time, now,
                trace_id=self._fit_trace, category="fit",
                attrs={"step": int(t)},
            )
        if self.reference_subspace is not None and v_bar is not None:
            from distributed_eigenspaces_tpu.ops.linalg import (
                principal_angles_degrees,
            )

            rec["principal_angle_deg"] = round(
                float(
                    principal_angles_degrees(
                        v_bar, self.reference_subspace
                    ).max()
                ),
                4,
            )
        self._last_time = now
        _stamp(rec)
        self.records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def attach_ingest(self, stats) -> "MetricsLogger":
        """Attach a live ``runtime.prefetch.PrefetchStats`` — its final
        counters land in ``summary()["ingest"]``, so ingest-bound vs
        compute-bound runs are diagnosable from the run report (the
        counters keep mutating as the stream runs; summary reads the
        state at call time)."""
        self.ingest_stats = stats
        return self

    def attach_compile(self, cache) -> "MetricsLogger":
        """Attach a live ``utils.compile_cache.CompileCache`` — its
        hit/miss/compile-ms counters land in ``summary()["compile"]``
        (read at summary time, like the ingest stats), so cold-start
        cost and cache effectiveness are diagnosable from the run
        report. An attached tracer is handed to the cache so compile
        hits/misses land on the exported timeline too."""
        self.compile_cache = cache
        if self.tracer is not None and getattr(cache, "tracer", None) is None:
            cache.tracer = self.tracer
        return self

    def attach_analysis(self, report) -> "MetricsLogger":
        """Attach a static-analysis verdict (``analysis.report``):
        either a finished report dict or a zero-arg callable producing
        one (e.g. ``lambda: engine_report(engine)``, evaluated at
        summary time so late-compiled bucket programs are audited
        too). Lands in ``summary()["analysis"]`` — the run report
        carries the contract verdict alongside the numbers it
        certifies. The attached report self-identifies via its
        ``schema`` key (``analysis-v2`` adds per-program ``shardings``
        annotation censuses); bench ``--compare`` condenses only the
        stable v1 keys, so records from either schema compare and a
        mismatch is noted, never fatal."""
        self.analysis_report = report
        return self

    def attach_serve_health(self, source) -> "MetricsLogger":
        """Attach a live read-path health source (a zero-arg callable
        returning a dict — ``QueryServer.health``). Multiple servers
        may attach (one per served signature); ``summary()["serving"]
        ["health"]`` merges them: counters sum, breaker states union,
        and the event-ledger counts (sheds / lane restarts / breaker
        trips) cover the whole run via the ring-buffer aggregates."""
        self.serve_health_sources.append(source)
        return self

    def attach_tracer(self, tracer) -> "MetricsLogger":
        """Attach a ``telemetry.Tracer``: per-step spans, serving /
        fleet / drift / fault spans from the instrumented components,
        and compile-cache events all record into ONE exportable
        timeline (``tracer.export_chrome_trace``)."""
        self.tracer = tracer
        if (
            self.compile_cache is not None
            and getattr(self.compile_cache, "tracer", None) is None
        ):
            self.compile_cache.tracer = tracer
        return self

    def fleet(self, event: dict) -> None:
        """Record one structured fleet-serving event — a dispatched fit
        bucket (``kind="bucket"``: tenant count, occupancy, signature,
        and the per-signature ``compile_stall_ms`` the dispatch paid
        acquiring its programs). Rides the same JSON stream as step
        records, tagged ``"fleet"``."""
        rec = {"fleet": event.get("kind", "bucket"), **event}
        _stamp(rec)
        self.fleet_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def serve(self, event: dict) -> None:
        """Record one structured serving event — a dispatched query
        micro-batch (``kind="batch"``: query count, per-query
        latencies + queue waits, occupancy, basis version, swap flag)
        or a drift refresh (``kind="drift"``: score, angle gap,
        published version). Rides the same JSON stream as step
        records, tagged ``"serve"``."""
        rec = {"serve": event.get("kind", "batch"), **event}
        _stamp(rec)
        self.serve_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def attach_membership(self, table) -> "MetricsLogger":
        """Attach a live ``runtime.membership.MembershipTable`` — its
        snapshot (per-slot states, generations, quorum) lands in
        ``summary()["membership"]["table"]`` (read at summary time,
        like the ingest stats)."""
        self.membership_table = table
        return self

    def membership(self, event: dict) -> None:
        """Record one structured membership event (an elastic-fleet
        lifecycle action or a closed round — ``runtime/membership.py``).
        Rides the same JSON stream as step records, tagged
        ``"membership"``."""
        rec = {"membership": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.membership_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def merge(self, event: dict) -> None:
        """Record one structured hierarchical-merge event (a tier-local
        round close, stale fold, or tier quorum transition —
        ``runtime/tiers.py``). Rides the same JSON stream as step
        records, tagged ``"merge"``."""
        rec = {"merge": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.merge_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def replication(self, event: dict) -> None:
        """Record one structured registry-replication event (a replica
        install with its propagation ``lag_ms``, a staleness-bound
        breach, a fenced zombie commit, or a publisher-lease failover —
        ``serving/replication.py``). Rides the same JSON stream as step
        records, tagged ``"replication"``."""
        rec = {"replication": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.replication_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def population(self, event: dict) -> None:
        """Record one structured population-ingest event (a cohort
        round close, a client quarantine with id + reason, a
        participation collapse/restore, or a hardened-merge stat —
        ``runtime/population.py``). Rides the same JSON stream as step
        records, tagged ``"population"``."""
        rec = {"population": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.population_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def solver(self, event: dict) -> None:
        """Record one structured eigensolver-convergence event
        (``kind="deflation"``: per-lane ``iters_used`` / ``residual``
        vectors from a gap-adaptive deflation solve, plus the armed
        ``tol`` and ``max_iters``; ``kind="subspace"``: the scalar
        equivalents from :func:`~..solvers.dist_subspace_eig`). Rides
        the same JSON stream as step records, tagged ``"solver"``."""
        rec = {"solver": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.solver_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def controller(self, event: dict) -> None:
        """Record one structured control-plane decision
        (``runtime/controller.py``): an autoscaler ``action`` /
        ``rollback`` with the full lineage ``{trigger, knob, from, to,
        plan_id}`` and the triggering telemetry evidence, a
        ``budget_exhausted`` freeze, or a lifecycle ``start``/``stop``.
        Rides the same JSON stream as step records, tagged
        ``"controller"``."""
        rec = {"controller": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.controller_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def fault(self, event: dict) -> None:
        """Record one structured fault event (a supervisor detection /
        recovery action). Events ride the same JSON stream as step
        records, tagged ``"fault"`` so consumers can split them."""
        rec = {"fault": event.get("kind", "unknown"), **event}
        _stamp(rec)
        self.fault_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    # -- eviction folds ------------------------------------------------------

    def _evict_step(self, rec: dict) -> None:
        agg = self._step_agg
        agg["steps"] += 1
        sps = rec.get("samples_per_sec")
        if sps is not None:
            agg["sps_sum"] += sps
            agg["sps_n"] += 1
            agg["sps_max"] = (
                sps if agg["sps_max"] is None else max(agg["sps_max"], sps)
            )

    def _evict_fault(self, rec: dict) -> None:
        agg = self._fault_agg
        agg["count"] += 1
        kind = rec.get("fault", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1

    def _evict_membership(self, rec: dict) -> None:
        agg = self._membership_agg
        agg["count"] += 1
        kind = rec.get("membership", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
        if kind == "round_closed":
            self._fold_membership_round(agg, rec)

    @staticmethod
    def _fold_membership_round(agg: dict, rec: dict) -> None:
        agg["rounds"] += 1
        if rec.get("deadline_closed"):
            agg["deadline_closed"] += 1
        agg["stale_folds"] += len(rec.get("stale") or ())
        arrived = rec.get("arrived")
        if arrived is not None:
            key = str(int(arrived))
            hist = agg["arrival_hist"]
            hist[key] = hist.get(key, 0) + 1

    def _evict_merge(self, rec: dict) -> None:
        agg = self._merge_agg
        agg["count"] += 1
        kind = rec.get("merge", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
        if kind == "tier_round":
            self._fold_merge_tier(agg["tiers"], rec)
        elif kind == "wire":
            self._fold_merge_wire(agg["wire"], rec)

    @staticmethod
    def _fold_merge_wire(wire: dict, rec: dict) -> None:
        """One per-tier wire-compression record (ISSUE 20,
        ``parallel/wire.tier_wire_records``) into the per-tier wire
        aggregate: cumulative payload bytes vs the fp32 program, the
        declared codec + its per-round compression ratio, and the
        error-feedback residual norm (last seen + running max) — the
        write-path twin of the serve dtype ledger."""
        tier = rec.get("tier", "unknown")
        t = wire.setdefault(tier, {
            "wire_dtype": rec.get("wire_dtype"), "rounds": 0,
            "payload_bytes": 0, "fp32_bytes": 0,
        })
        t["rounds"] += 1
        t["wire_dtype"] = rec.get("wire_dtype", t["wire_dtype"])
        t["payload_bytes"] += int(rec.get("payload_bytes") or 0)
        t["fp32_bytes"] += int(rec.get("fp32_bytes") or 0)
        if rec.get("compression_ratio") is not None:
            t["compression_ratio"] = rec["compression_ratio"]
        norm = rec.get("ef_residual_norm")
        if norm is not None:
            t["ef_residual_norm"] = float(norm)
            t["ef_residual_norm_max"] = max(
                float(norm), t.get("ef_residual_norm_max", 0.0)
            )

    @staticmethod
    def _fold_merge_tier(tiers: dict, rec: dict) -> None:
        """One tier-round record into the per-tier aggregate — the
        membership round fold, keyed by tier name (the tree shape is
        part of the ledger: fan-in rides every record)."""
        tier = rec.get("tier", "unknown")
        t = tiers.setdefault(tier, {
            "fan_in": rec.get("fan_in"), "rounds": 0,
            "deadline_closed": 0, "stale_folds": 0, "arrival_hist": {},
        })
        t["rounds"] += 1
        if rec.get("deadline_closed"):
            t["deadline_closed"] += 1
        t["stale_folds"] += len(rec.get("stale") or ())
        arrived = rec.get("arrived")
        if arrived is not None:
            key = str(int(arrived))
            t["arrival_hist"][key] = t["arrival_hist"].get(key, 0) + 1

    def _evict_population(self, rec: dict) -> None:
        agg = self._population_agg
        agg["count"] += 1
        kind = rec.get("population", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
        self._fold_population(agg, rec)

    @staticmethod
    def _fold_population(agg: dict, rec: dict) -> None:
        """One population-ingest record into the aggregate: cohort
        rounds bucket participation into a decile histogram (the
        membership arrival-hist rule, normalized because cohorts are
        sampled, not slotted), quarantines tally by rejection reason,
        merge stats feed the running trim-fraction mean."""
        kind = rec.get("population", "unknown")
        if kind == "round_closed":
            agg["rounds"] += 1
            agg["stale_folds"] += int(rec.get("stale") or 0)
            p = rec.get("participation")
            if p is not None:
                key = f"{int(float(p) * 10) / 10:.1f}"
                hist = agg["participation_hist"]
                hist[key] = hist.get(key, 0) + 1
        elif kind == "quarantine_client":
            reason = rec.get("reason", "unknown")
            rej = agg["rejects_by_reason"]
            rej[reason] = rej.get(reason, 0) + 1
        elif kind == "merge":
            tf = rec.get("trim_frac")
            if tf is not None:
                agg["trim_frac_sum"] += float(tf)
                agg["trim_frac_n"] += 1

    def _evict_solver(self, rec: dict) -> None:
        agg = self._solver_agg
        agg["count"] += 1
        kind = rec.get("solver", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
        self._fold_solver(agg, rec)

    @staticmethod
    def _fold_solver(agg: dict, rec: dict) -> None:
        """One solver-convergence record into the aggregate: per-lane
        iteration totals (sum / max / solve count) plus how often the
        lane stopped EARLY (``iters_used < max_iters`` — the
        gap-adaptive win the counters exist to show). Scalar
        ``iters_used`` folds as a single lane 0."""
        used = rec.get("iters_used")
        if used is None:
            return
        if not isinstance(used, (list, tuple)):
            used = [used]
        max_iters = rec.get("max_iters")
        by_lane = agg["by_lane"]
        for lane, n in enumerate(used):
            st = by_lane.setdefault(
                lane,
                {"solves": 0, "iters_sum": 0, "iters_max": 0,
                 "early_stops": 0},
            )
            n = int(n)
            st["solves"] += 1
            st["iters_sum"] += n
            st["iters_max"] = max(st["iters_max"], n)
            if max_iters is not None and n < int(max_iters):
                st["early_stops"] += 1

    def _evict_controller(self, rec: dict) -> None:
        agg = self._controller_agg
        agg["count"] += 1
        kind = rec.get("controller", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
        self._fold_controller(agg, rec)

    @staticmethod
    def _fold_controller(agg: dict, rec: dict) -> None:
        """One control-plane decision into the aggregate: per-knob
        action counts plus the rollback total — the numbers the
        A/B gates read even after the decision records themselves
        evicted."""
        kind = rec.get("controller")
        if kind in ("action", "rollback"):
            knob = rec.get("knob", "unknown")
            agg["by_knob"][knob] = agg["by_knob"].get(knob, 0) + 1
        if kind == "rollback":
            agg["rollbacks"] += 1

    def _controller_summary(self) -> dict:
        """The ``summary()["controller"]`` section (ISSUE 19): every
        retained control-plane decision verbatim — lineage ``{trigger,
        knob, from, to, plan_id}`` plus the telemetry evidence that
        triggered it — with counts by kind / by knob and the rollback
        total covering the whole run (evictions folded)."""
        agg = {
            "count": self._controller_agg["count"],
            "by_kind": dict(self._controller_agg["by_kind"]),
            "by_knob": dict(self._controller_agg["by_knob"]),
            "rollbacks": self._controller_agg["rollbacks"],
        }
        for r in self.controller_records:
            agg["count"] += 1
            kind = r.get("controller", "unknown")
            agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
            self._fold_controller(agg, r)
        out: dict = {
            "decisions": agg["count"],
            "by_kind": agg["by_kind"],
            "rollbacks": agg["rollbacks"],
            # the events list holds the RETAINED window; evicted
            # decisions survive in the counters above
            "events": list(self.controller_records),
        }
        if agg["by_knob"]:
            out["by_knob"] = agg["by_knob"]
        if self.controller_records.evicted:
            out["events_evicted"] = self.controller_records.evicted
        return out

    def _solver_summary(self) -> dict:
        """Per-lane convergence counters (ISSUE 18): for each deflation
        lane, solve count, mean/max iterations, and the early-stop
        count the gap-adaptive criterion earned — live window + evicted
        aggregate."""
        agg = {
            "count": self._solver_agg["count"],
            "by_kind": dict(self._solver_agg["by_kind"]),
            "by_lane": {
                lane: dict(st)
                for lane, st in self._solver_agg["by_lane"].items()
            },
        }
        for r in self.solver_records:
            agg["count"] += 1
            kind = r.get("solver", "unknown")
            agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
            self._fold_solver(agg, r)
        out: dict = {
            "solves": agg["count"], "by_kind": agg["by_kind"],
        }
        lanes = {}
        for lane in sorted(agg["by_lane"]):
            st = agg["by_lane"][lane]
            lanes[str(lane)] = {
                "solves": st["solves"],
                "mean_iters": round(st["iters_sum"] / st["solves"], 2),
                "max_iters": st["iters_max"],
                "early_stops": st["early_stops"],
            }
        if lanes:
            out["by_lane"] = lanes
        return out

    def _evict_replication(self, rec: dict) -> None:
        agg = self._replication_agg
        agg["count"] += 1
        self._fold_replication(agg, rec)
        if rec.get("replication") == "install":
            lag = rec.get("lag_ms")
            if lag is not None:
                # histograms carry seconds everywhere else; keep the
                # unit and convert back at report time
                agg["lag_hist"].record(max(float(lag), 1e-3) / 1e3)

    @staticmethod
    def _fold_replication(agg: dict, rec: dict) -> None:
        """One replication event into the counter aggregate — shared by
        eviction and the live-window pass in the summary builder."""
        kind = rec.get("replication", "unknown")
        agg["by_kind"][kind] = agg["by_kind"].get(kind, 0) + 1
        if kind == "install":
            agg["installs"] += 1
        elif kind == "stale":
            agg["stale"] += 1
        elif kind == "fenced":
            agg["fenced"] += 1
        elif kind == "failover":
            agg["failovers"] += 1
            if rec.get("recovery_ms") is not None:
                agg["recovery_ms"].append(
                    round(float(rec["recovery_ms"]), 3)
                )

    def _evict_serve(self, rec: dict) -> None:
        if rec.get("serve") == "drift":
            self._serve_agg["drifts"] += 1
            return
        if rec.get("serve") == "shed":
            reason = rec.get("reason", "overload")
            by = self._serve_agg["sheds_by_reason"]
            by[reason] = by.get(reason, 0) + rec.get("dropped", 1)
            return
        if rec.get("serve") == "lane":
            if rec.get("event") == "restart":
                self._serve_agg["lane_restarts"] += 1
            elif rec.get("event") == "dead":
                self._serve_agg["lane_deaths"] += 1
            return
        if rec.get("serve") == "breaker":
            if rec.get("event") == "open":
                self._serve_agg["breaker_trips"] += 1
            return
        if rec.get("serve") == "batch":
            self._fold_dispatch(
                self._serve_agg, rec, "queries", self.slo_p99_ms
            )

    def _evict_fleet(self, rec: dict) -> None:
        if rec.get("fleet") == "bucket":
            self._fold_dispatch(
                self._fleet_agg, rec, "tenants", self.fleet_slo_p99_ms
            )

    def _fold_dispatch(self, agg: dict, rec: dict, req_key: str,
                       slo_ms: float | None) -> None:
        """One evicted serve batch / fleet bucket into the running
        aggregate — the counters :meth:`summary` adds back, and the
        histograms its percentiles/decomposition merge with the live
        window."""
        agg["events"] += 1
        agg["requests"] += rec.get(req_key, 0)
        agg["rejected"] += rec.get("rejected", 0)
        if rec.get("swap"):
            agg["swaps"] += 1
        if "occupancy" in rec:
            agg["occ_sum"] += rec["occupancy"]
            agg["occ_n"] += 1
        pad = rec.get("padded_rows", 0)
        agg["padded_rows"] += pad
        if pad and "signature" in rec:
            sig = str(tuple(rec["signature"]))
            agg["padded_by_sig"][sig] = (
                agg["padded_by_sig"].get(sig, 0) + pad
            )
        lpad = rec.get("padded_lanes", 0)
        agg["padded_lanes"] += lpad
        if lpad and "signature" in rec:
            sig = str(tuple(rec["signature"]))
            agg["padded_lanes_by_sig"][sig] = (
                agg["padded_lanes_by_sig"].get(sig, 0) + lpad
            )
        ff = rec.get("fill_fraction")
        if ff is not None:
            agg["fill_sum"] += float(ff)
            agg["fill_n"] += 1
        for a in rec.get("admit_to_dispatch_s") or ():
            if a is not None:
                agg["hist"]["admit_to_dispatch_s"].record(
                    max(float(a), 1e-6)
                )
        agg["compile_misses"] += rec.get("compile_misses", 0)
        stall = rec.get("compile_stall_ms", 0.0)
        agg["compile_stall_ms"] += stall
        if stall and "signature" in rec:
            sig = str(tuple(rec["signature"]))
            agg["by_sig"][sig] = round(
                agg["by_sig"].get(sig, 0.0) + stall, 3
            )
        if "version" in rec:
            agg["versions"].add(rec["version"])
        t = rec.get("t_mono", rec.get("t"))
        if t is not None:
            agg["t_min"] = t if agg["t_min"] is None else min(agg["t_min"], t)
            agg["t_max"] = t if agg["t_max"] is None else max(agg["t_max"], t)
        for row in self._decomp_rows(rec):
            agg["hist"]["total_s"].record(row["total_s"])
            for k in DECOMP_KEYS:
                if row.get(k) is not None:
                    agg["hist"][k].record(row[k])
            if slo_ms is not None:
                agg["slo_requests"] += 1
                if row["total_s"] * 1e3 > slo_ms:
                    agg["slo_violations"] += 1

    # -- decomposition -------------------------------------------------------

    @staticmethod
    def _decomp_rows(rec: dict) -> list[dict]:
        """Per-request latency rows for one dispatch event. Every row
        has ``total_s``; the component keys are present when the event
        carried the ISSUE-6 fields (``queue_wait_s`` list +
        ``compute_s``), and then satisfy
        ``total = queue_wait + compile_stall + compute + other``
        exactly — the batch's compile stall and compute are shared by
        every request that rode it (each waited through both)."""
        lats = rec.get("query_latency_s") or rec.get("request_latency_s")
        if not lats:
            return []
        qws = rec.get("queue_wait_s")
        stall_s = (rec.get("compile_stall_ms") or 0.0) / 1e3
        compute = rec.get("compute_s")
        rows = []
        for i, lat in enumerate(lats):
            if lat is None:
                continue
            row: dict = {"total_s": float(lat)}
            qw = qws[i] if qws is not None and i < len(qws) else None
            if qw is not None and compute is not None:
                row["queue_wait_s"] = float(qw)
                row["compile_stall_s"] = stall_s
                row["compute_s"] = float(compute)
                row["other_s"] = max(
                    0.0, float(lat) - float(qw) - stall_s - float(compute)
                )
            rows.append(row)
        return rows

    def summary(self) -> dict:
        """Aggregate: total steps, mean/max throughput, final accuracy,
        the fault ledger when any fault was recorded, the serving /
        fleet dispatch sections (latency percentiles + decomposition),
        and — when an SLO target is declared — the ``"slo"`` section
        (attainment, error-budget burn). Ring-buffer evictions are
        already folded in: counts and percentiles cover the whole run,
        not just the retained window."""
        agg = self._step_agg
        out: dict = {"steps": agg["steps"] + len(self.records)}
        sps = [
            r["samples_per_sec"] for r in self.records
            if "samples_per_sec" in r
        ]
        sps_n = agg["sps_n"] + len(sps)
        if sps_n:
            out["mean_samples_per_sec"] = round(
                (agg["sps_sum"] + sum(sps)) / sps_n, 1
            )
            live_max = max(sps) if sps else None
            out["max_samples_per_sec"] = round(
                max(
                    v for v in (agg["sps_max"], live_max)
                    if v is not None
                ),
                1,
            )
        angles = [
            r["principal_angle_deg"]
            for r in self.records
            if "principal_angle_deg" in r
        ]
        if angles:
            out["final_principal_angle_deg"] = angles[-1]
        if self.fault_records or self._fault_agg["count"]:
            by_kind = dict(self._fault_agg["by_kind"])
            for r in self.fault_records:
                by_kind[r["fault"]] = by_kind.get(r["fault"], 0) + 1
            out["faults"] = {
                "count": self._fault_agg["count"] + len(self.fault_records),
                "by_kind": by_kind,
                # the events list holds the RETAINED window; evicted
                # events survive in count/by_kind above
                "events": list(self.fault_records),
            }
            if self.fault_records.evicted:
                out["faults"]["events_evicted"] = self.fault_records.evicted
        if self.ingest_stats is not None:
            out["ingest"] = self.ingest_stats.as_dict()
        if (
            self.membership_records
            or self._membership_agg["count"]
            or self.membership_table is not None
        ):
            out["membership"] = self._membership_summary()
        if self.merge_records or self._merge_agg["count"]:
            out["merge"] = self._merge_summary()
        if self.replication_records or self._replication_agg["count"]:
            out["replication"] = self._replication_summary()
        if self.population_records or self._population_agg["count"]:
            out["population"] = self._population_summary()
        if self.solver_records or self._solver_agg["count"]:
            out["solver"] = self._solver_summary()
        if self.controller_records or self._controller_agg["count"]:
            out["controller"] = self._controller_summary()
        if self.serve_records or self._serve_agg["events"]:
            out["serving"] = self._serving_summary()
        if self.fleet_records or self._fleet_agg["events"]:
            out["fleet"] = self._fleet_summary()
        slo = self._slo_summary(out)
        if slo:
            out["slo"] = slo
        episodes = self._episode_summaries()
        if episodes:
            out["episodes"] = episodes
        if self.compile_cache is not None:
            out["compile"] = self.compile_cache.stats()
        if self.analysis_report is not None:
            rep = self.analysis_report
            out["analysis"] = rep() if callable(rep) else rep
        return out

    # -- dispatch-section helpers --------------------------------------------

    @staticmethod
    def _stall_fields(records: list[dict], agg: dict) -> dict:
        """Shared compile-stall aggregation for the serving and fleet
        sections: total misses, total stall ms, and the per-signature
        stall breakdown that makes a p99 regression attributable to
        the exact shape that compiled inline."""
        out: dict = {
            "compile_misses": agg["compile_misses"] + sum(
                r.get("compile_misses", 0) for r in records
            ),
            "compile_stall_ms": round(
                agg["compile_stall_ms"] + sum(
                    r.get("compile_stall_ms", 0.0) for r in records
                ),
                3,
            ),
        }
        by_sig: dict[str, float] = dict(agg["by_sig"])
        for r in records:
            stall = r.get("compile_stall_ms", 0.0)
            if stall and "signature" in r:
                sig = str(tuple(r["signature"]))
                by_sig[sig] = round(by_sig.get(sig, 0.0) + stall, 3)
        if by_sig:
            out["compile_stall_ms_by_signature"] = by_sig
        return out

    def _occupancy_fields(self, batches: list[dict], agg: dict) -> dict:
        """Batch-occupancy metrics for the serving section (ISSUE 17):
        mean fill fraction (served rows / dispatched rows after bucket
        padding), padded-row waste per signature bucket, and
        admit-to-dispatch wait p50/p99 — the number continuous batching
        exists to shrink. Percentiles follow the latency-section rule:
        exact over the live window, log-bucket histogram estimates once
        the ring has evicted."""
        out: dict = {}
        fills = [
            r["fill_fraction"] for r in batches if "fill_fraction" in r
        ]
        fill_n = agg["fill_n"] + len(fills)
        if fill_n:
            out["mean_fill_fraction"] = round(
                (agg["fill_sum"] + sum(fills)) / fill_n, 4
            )
        total_pad = agg["padded_rows"] + sum(
            r.get("padded_rows", 0) for r in batches
        )
        if total_pad:
            out["padded_rows"] = total_pad
            by_sig: dict[str, int] = dict(agg["padded_by_sig"])
            for r in batches:
                pad = r.get("padded_rows", 0)
                if pad and "signature" in r:
                    sig = str(tuple(r["signature"]))
                    by_sig[sig] = by_sig.get(sig, 0) + pad
            if by_sig:
                out["padded_rows_by_signature"] = by_sig
        total_lpad = agg["padded_lanes"] + sum(
            r.get("padded_lanes", 0) for r in batches
        )
        if total_lpad:
            out["padded_lanes"] = total_lpad
            by_sig_l: dict[str, int] = dict(agg["padded_lanes_by_sig"])
            for r in batches:
                lpad = r.get("padded_lanes", 0)
                if lpad and "signature" in r:
                    sig = str(tuple(r["signature"]))
                    by_sig_l[sig] = by_sig_l.get(sig, 0) + lpad
            if by_sig_l:
                out["padded_lanes_by_signature"] = by_sig_l
        admits = [
            float(a)
            for r in batches
            for a in (r.get("admit_to_dispatch_s") or ())
            if a is not None
        ]
        evicted = agg["hist"]["admit_to_dispatch_s"].count > 0
        if admits and not evicted:
            ws = sorted(admits)
            out["admit_to_dispatch_p50_s"] = round(ws[len(ws) // 2], 6)
            out["admit_to_dispatch_p99_s"] = round(
                ws[min(len(ws) - 1, int(len(ws) * 0.99))], 6
            )
        elif evicted:
            h = agg["hist"]["admit_to_dispatch_s"].copy()
            h.record_many(max(a, 1e-6) for a in admits)
            out["admit_to_dispatch_p50_s"] = round(
                h.quantile(0.5) or 0.0, 6
            )
            out["admit_to_dispatch_p99_s"] = round(
                h.quantile(0.99) or 0.0, 6
            )
        return out

    def _latency_fields(self, records: list[dict], agg: dict) -> dict:
        """p50/p99 + decomposition for one dispatch section. With no
        evictions the percentiles are EXACT (sorted live latencies —
        bit-compatible with the pre-ISSUE-6 summary); once the ring
        has evicted, live rows merge into the eviction histograms and
        the percentiles are log-bucket estimates (within one bucket
        growth factor — ``telemetry.Histogram``)."""
        out: dict = {}
        rows = [row for r in records for row in self._decomp_rows(r)]
        evicted = agg["hist"]["total_s"].count > 0
        if not rows and not evicted:
            return out
        if not evicted:
            lat = sorted(row["total_s"] for row in rows)
            out["p50_latency_s"] = round(lat[len(lat) // 2], 6)
            out["p99_latency_s"] = round(
                lat[min(len(lat) - 1, int(len(lat) * 0.99))], 6
            )
        else:
            h = agg["hist"]["total_s"].copy()
            h.record_many(row["total_s"] for row in rows)
            out["p50_latency_s"] = round(h.quantile(0.5), 6)
            out["p99_latency_s"] = round(h.quantile(0.99), 6)
            out["latency_hist"] = h.as_dict()
        decomp = self._decomposition(rows, agg, evicted)
        if decomp:
            out["latency_decomposition"] = decomp
        return out

    def _decomposition(self, rows: list[dict], agg: dict,
                       evicted: bool) -> dict | None:
        """The latency decomposition block: per-percentile component
        breakdown. Exact mode reports the COMPONENTS OF the request at
        the percentile rank (so they sum to its total, ±rounding);
        histogram mode (after eviction) reports per-component
        percentile estimates and labels itself accordingly."""
        full = [r for r in rows if "queue_wait_s" in r]
        if not evicted:
            if not full:
                return None
            full.sort(key=lambda r: r["total_s"])
            n = len(full)

            def pick(rank: int) -> dict:
                r = full[rank]
                return {
                    "total_s": round(r["total_s"], 6),
                    **{k: round(r[k], 6) for k in DECOMP_KEYS},
                }

            mean = {
                "total_s": round(
                    sum(r["total_s"] for r in full) / n, 6
                ),
                **{
                    k: round(sum(r[k] for r in full) / n, 6)
                    for k in DECOMP_KEYS
                },
            }
            return {
                "source": "exact",
                "requests": n,
                "p50": pick(n // 2),
                "p99": pick(min(n - 1, int(n * 0.99))),
                "mean": mean,
            }
        # histogram mode: merge live rows into copies of the evicted
        # histograms, report per-component estimates
        hists = {k: agg["hist"][k].copy() for k in DECOMP_KEYS}
        total = agg["hist"]["total_s"].copy()
        for r in full:
            for k in DECOMP_KEYS:
                hists[k].record(r[k])
        total.record_many(r["total_s"] for r in rows)
        if not any(h.count for h in hists.values()):
            return None

        def est(q: float) -> dict:
            return {
                "total_s": round(total.quantile(q) or 0.0, 6),
                **{
                    k: round(hists[k].quantile(q) or 0.0, 6)
                    for k in DECOMP_KEYS
                },
            }

        return {
            "source": "histogram",
            "requests": total.count,
            "p50": est(0.5),
            "p99": est(0.99),
            "mean": {
                "total_s": round(total.mean or 0.0, 6),
                **{
                    k: round(hists[k].mean or 0.0, 6)
                    for k in DECOMP_KEYS
                },
            },
        }

    def _membership_summary(self) -> dict:
        """The ``summary()["membership"]`` section (ISSUE 8): event
        counts by kind (joins, admits, leaves, suspect→dead, quorum
        transitions), round outcomes (deadline-closed rounds, stale
        straggler folds, per-round arrival histogram), the retained
        event window, and — when a table is attached — its live
        snapshot. Evictions are folded in, so the counts cover the
        whole run."""
        agg = self._membership_agg
        by_kind = dict(agg["by_kind"])
        rounds = {
            "rounds": agg["rounds"],
            "deadline_closed": agg["deadline_closed"],
            "stale_folds": agg["stale_folds"],
            "arrival_hist": dict(agg["arrival_hist"]),
        }
        for r in self.membership_records:
            kind = r.get("membership", "unknown")
            by_kind[kind] = by_kind.get(kind, 0) + 1
            if kind == "round_closed":
                self._fold_membership_round(rounds, r)
        out: dict = {
            "events": agg["count"] + len(self.membership_records),
            "by_kind": by_kind,
            **rounds,
            # the retained window — evicted events survive in the
            # counters above (the faults-section rule)
            "recent": list(self.membership_records),
        }
        if self.membership_records.evicted:
            out["events_evicted"] = self.membership_records.evicted
        if self.membership_table is not None:
            out["table"] = self.membership_table.snapshot()
        return out

    def _merge_summary(self) -> dict:
        """The ``summary()["merge"]`` section (ISSUE 12): hierarchical-
        merge event counts by kind and the PER-TIER round ledger —
        fan-in, rounds, tier-deadline closes, one-step-stale folds, and
        the per-round arrival histogram — plus, under an active
        ``merge_wire_dtype`` policy, the per-tier WIRE ledger (ISSUE
        20: codec, payload vs fp32 bytes, compression ratio, EF
        residual norm) and the retained event window. Evictions are
        folded in (the membership-section rule), so a long elastic
        run's tree stays fully accounted."""
        agg = self._merge_agg
        by_kind = dict(agg["by_kind"])
        tiers = {
            name: {**t, "arrival_hist": dict(t["arrival_hist"])}
            for name, t in agg["tiers"].items()
        }
        wire = {name: dict(t) for name, t in agg["wire"].items()}
        for r in self.merge_records:
            kind = r.get("merge", "unknown")
            by_kind[kind] = by_kind.get(kind, 0) + 1
            if kind == "tier_round":
                self._fold_merge_tier(tiers, r)
            elif kind == "wire":
                self._fold_merge_wire(wire, r)
        out: dict = {
            "events": agg["count"] + len(self.merge_records),
            "by_kind": by_kind,
            "tiers": tiers,
            "recent": list(self.merge_records),
        }
        if wire:
            out["wire"] = wire
        if self.merge_records.evicted:
            out["events_evicted"] = self.merge_records.evicted
        return out

    def _population_summary(self) -> dict:
        """The ``summary()["population"]`` section (ISSUE 16): event
        counts by kind, cohort-round outcomes (rounds, one-step-stale
        folds, per-round participation decile histogram), quarantines
        by rejection reason (the attribution ledger's roll-up), the
        mean trimmed-merge trim fraction, and the retained event
        window. Evictions are folded in (the membership-section rule),
        so the counts cover the whole run."""
        agg = self._population_agg
        folded = {
            "by_kind": dict(agg["by_kind"]),
            "rounds": agg["rounds"],
            "stale_folds": agg["stale_folds"],
            "participation_hist": dict(agg["participation_hist"]),
            "rejects_by_reason": dict(agg["rejects_by_reason"]),
            "trim_frac_sum": agg["trim_frac_sum"],
            "trim_frac_n": agg["trim_frac_n"],
        }
        for r in self.population_records:
            kind = r.get("population", "unknown")
            folded["by_kind"][kind] = folded["by_kind"].get(kind, 0) + 1
            self._fold_population(folded, r)
        out: dict = {
            "events": agg["count"] + len(self.population_records),
            "by_kind": folded["by_kind"],
            "rounds": folded["rounds"],
            "stale_folds": folded["stale_folds"],
            "participation_hist": folded["participation_hist"],
            "rejects_by_reason": folded["rejects_by_reason"],
            "recent": list(self.population_records),
        }
        if folded["trim_frac_n"]:
            out["mean_trim_frac"] = round(
                folded["trim_frac_sum"] / folded["trim_frac_n"], 4
            )
        if self.population_records.evicted:
            out["events_evicted"] = self.population_records.evicted
        return out

    def _replication_summary(self) -> dict:
        """The ``summary()["replication"]`` section (ISSUE 14): event
        counts by kind, replica installs / staleness breaches / fenced
        zombie commits, propagation-lag percentiles (exact over the
        live window; log-bucket histogram estimates once the ring has
        evicted — the latency-section rule), failover count + per-
        failover recovery_ms, and the retained event window."""
        agg = self._replication_agg
        fold = {
            "by_kind": dict(agg["by_kind"]), "installs": agg["installs"],
            "stale": agg["stale"], "fenced": agg["fenced"],
            "failovers": agg["failovers"],
            "recovery_ms": list(agg["recovery_ms"]),
        }
        live_lags: list[float] = []
        for r in self.replication_records:
            self._fold_replication(fold, r)
            if (
                r.get("replication") == "install"
                and r.get("lag_ms") is not None
            ):
                live_lags.append(float(r["lag_ms"]))
        out: dict = {
            "events": agg["count"] + len(self.replication_records),
            "by_kind": fold["by_kind"],
            "installs": fold["installs"],
            "stale": fold["stale"],
            "fenced": fold["fenced"],
            "failovers": fold["failovers"],
        }
        evicted = agg["lag_hist"].count > 0
        if live_lags and not evicted:
            lat = sorted(live_lags)
            out["propagation_p50_ms"] = round(lat[len(lat) // 2], 3)
            out["propagation_p99_ms"] = round(
                lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3
            )
        elif evicted:
            h = agg["lag_hist"].copy()
            h.record_many(max(v, 1e-3) / 1e3 for v in live_lags)
            out["propagation_p50_ms"] = round(
                (h.quantile(0.5) or 0.0) * 1e3, 3
            )
            out["propagation_p99_ms"] = round(
                (h.quantile(0.99) or 0.0) * 1e3, 3
            )
            out["lag_hist"] = h.as_dict()
        if fold["recovery_ms"]:
            out["failover_recovery_ms"] = fold["recovery_ms"]
        out["recent"] = list(self.replication_records)
        if self.replication_records.evicted:
            out["events_evicted"] = self.replication_records.evicted
        return out

    def _fleet_summary(self) -> dict:
        """The ``summary()["fleet"]`` section (mirrors ``["serving"]``):
        dispatched buckets, tenants served, mean bucket occupancy,
        request-latency percentiles + decomposition, and the
        compile-stall ledger."""
        agg = self._fleet_agg
        buckets = [
            r for r in self.fleet_records if r["fleet"] == "bucket"
        ]
        out: dict = {"buckets": agg["events"] + len(buckets)}
        if buckets or agg["events"]:
            out["tenants"] = agg["requests"] + sum(
                r.get("tenants", 0) for r in buckets
            )
            occ = [r["occupancy"] for r in buckets if "occupancy" in r]
            occ_n = agg["occ_n"] + len(occ)
            if occ_n:
                out["mean_occupancy"] = round(
                    (agg["occ_sum"] + sum(occ)) / occ_n, 4
                )
            # occupancy-waste ledger (ISSUE 18: heterogeneous-k
            # bucketing surfaces padded_lanes[_by_signature] here)
            out.update(self._occupancy_fields(buckets, agg))
            out.update(self._stall_fields(buckets, agg))
            out.update(self._latency_fields(buckets, agg))
        if self.fleet_records.evicted:
            out["events_evicted"] = self.fleet_records.evicted
        return out

    def _serving_summary(self) -> dict:
        """The ``summary()["serving"]`` section (mirrors ``["ingest"]``):
        qps over the served window, p50/p99 query latency decomposed
        into queue_wait / compile_stall / compute / other, mean batch
        occupancy, hot-swap count, and the latest drift score."""
        agg = self._serve_agg
        batches = [r for r in self.serve_records if r["serve"] == "batch"]
        out: dict = {"batches": agg["events"] + len(batches)}
        if batches or agg["events"]:
            live_q = sum(r.get("queries", 0) for r in batches)
            queries = agg["requests"] + live_q
            out["queries"] = queries
            out["rejected"] = agg["rejected"] + sum(
                r.get("rejected", 0) for r in batches
            )
            ts = [r["t_mono"] for r in batches] + [
                t for t in (agg["t_min"], agg["t_max"]) if t is not None
            ]
            span = (max(ts) - min(ts)) if ts else 0.0
            n_events = agg["events"] + len(batches)
            if n_events > 1 and span > 0:
                # arrival-window rate; a single batch has no window, so
                # its own dispatch time is the only honest denominator
                out["qps"] = round(queries / span, 1)
            else:
                secs = sum(r.get("batch_seconds", 0.0) for r in batches)
                if secs > 0:
                    out["qps"] = round(queries / secs, 1)
            occ = [r["occupancy"] for r in batches if "occupancy" in r]
            occ_n = agg["occ_n"] + len(occ)
            if occ_n:
                out["mean_occupancy"] = round(
                    (agg["occ_sum"] + sum(occ)) / occ_n, 4
                )
            out["swaps"] = agg["swaps"] + sum(
                1 for r in batches if r.get("swap")
            )
            versions = set(agg["versions"]) | {
                r["version"] for r in batches if "version" in r
            }
            out["versions_served"] = sorted(versions)
            out.update(self._occupancy_fields(batches, agg))
            out.update(self._stall_fields(batches, agg))
            out.update(self._latency_fields(batches, agg))
        health = self._health_summary()
        if health:
            out["health"] = health
        drifts = [r for r in self.serve_records if r["serve"] == "drift"]
        if drifts or agg["drifts"]:
            out["drift_refreshes"] = agg["drifts"] + len(drifts)
        if drifts:
            out["drift_score"] = drifts[-1].get("score")
            out["drift_published"] = [
                r["published"] for r in drifts
                if r.get("published") is not None
            ]
        if self.serve_records.evicted:
            out["events_evicted"] = self.serve_records.evicted
        return out

    def _health_summary(self) -> dict:
        """``summary()["serving"]["health"]`` (ISSUE 7): the read
        path's resilience report. Counters (sheds by reason, lane
        restarts/deaths, breaker trips, recovery time) come from the
        EVENT stream — live window plus eviction aggregates, so they
        cover the whole run; the live snapshot (breaker states,
        in-flight depth, lane liveness) comes from the attached
        :meth:`attach_serve_health` sources — states, not counts, so
        multi-server merges never double-count."""
        agg = self._serve_agg
        sheds = dict(agg["sheds_by_reason"])
        lane_restarts = agg["lane_restarts"]
        lane_deaths = agg["lane_deaths"]
        breaker_trips = agg["breaker_trips"]
        recovery_ms = None
        for r in self.serve_records:
            kind = r.get("serve")
            if kind == "shed":
                reason = r.get("reason", "overload")
                sheds[reason] = sheds.get(reason, 0) + r.get("dropped", 1)
            elif kind == "lane":
                if r.get("event") == "restart":
                    lane_restarts += 1
                elif r.get("event") == "dead":
                    lane_deaths += 1
                elif r.get("event") == "recovered":
                    recovery_ms = r.get("recovery_ms")
            elif kind == "breaker" and r.get("event") == "open":
                breaker_trips += 1
        out: dict = {}
        if sheds:
            out["sheds"] = sheds
            out["shed_count"] = sum(sheds.values())
        if lane_restarts:
            out["lane_restarts"] = lane_restarts
        if lane_deaths:
            out["lane_deaths"] = lane_deaths
        if breaker_trips:
            out["breaker_trips"] = breaker_trips
        if recovery_ms is not None:
            out["recovery_ms"] = recovery_ms
        # live state from attached servers: breaker states union,
        # in-flight sum, lane liveness
        breakers: dict = {}
        inflight = 0
        lanes_alive: list[bool] = []
        for src in self.serve_health_sources:
            try:
                live = src()
            except Exception:
                continue
            breakers.update(live.get("breakers") or {})
            inflight += live.get("inflight", 0)
            if "lane_alive" in live:
                lanes_alive.append(bool(live["lane_alive"]))
            if live.get("last_recovery_ms") is not None:
                recovery_ms = live["last_recovery_ms"]
                out["recovery_ms"] = recovery_ms
        if breakers:
            out["breakers"] = breakers
        if self.serve_health_sources:
            out["inflight"] = inflight
            out["servers"] = len(self.serve_health_sources)
            if lanes_alive:
                out["lanes_alive"] = all(lanes_alive)
        return out

    @staticmethod
    def _recovery_from(
        t0: float, completions: list, target_ms: float, probe: int = 5
    ) -> float | None:
        """Recovery time (ms) from a fault injected at monotonic ``t0``
        back to SLO-attaining steady state: the earliest completion at
        or after ``t0`` from which the next ``probe`` consecutive
        requests (or all that remain, if fewer) ALL meet the target —
        one lucky fast request during the incident doesn't count as
        recovered. ``completions`` is the time-sorted
        ``(t_mono, latency_ms)`` stream; returns None when steady
        state was never regained."""
        for i in range(len(completions)):
            if completions[i][0] < t0:
                continue
            k = min(probe, len(completions) - i)
            if all(
                completions[j][1] <= target_ms for j in range(i, i + k)
            ):
                return round((completions[i][0] - t0) * 1e3, 3)
        return None

    def _episode_summaries(self) -> dict:
        """The ``summary()["episodes"]`` section (ISSUE 11): per-tier
        records sliced by the attached tracer's ``category="episode"``
        spans (``Tracer.episode`` — the scenario harness's markers).
        Each episode reports the SAME key set (None/0 when a field
        does not apply) so two runs of one spec produce structurally
        identical verdicts: window SLO attainment + burn, p99 and its
        queue_wait/compile_stall/compute decomposition, shed / lane /
        breaker / drift counts, fleet requests, membership events, and
        — for fault episodes — recovery back to SLO-attaining steady
        state. Slicing covers the RETAINED ring window (size scenario
        runs under ``retention``; a sliced long run under-counts
        loudly via ``events_evicted`` in the per-tier sections)."""
        tracer = self.tracer
        if tracer is None:
            return {}
        ep_spans = [
            sp for sp in tracer.snapshot() if sp.category == "episode"
        ]
        if not ep_spans:
            return {}
        batches = [
            r for r in self.serve_records if r.get("serve") == "batch"
        ]
        serve_events = list(self.serve_records)
        fleet_buckets = [
            r for r in self.fleet_records if r.get("fleet") == "bucket"
        ]
        membership = list(self.membership_records)
        # per-request completion stream for recovery scans: a request
        # completes at its batch's dispatch stamp
        completions = sorted(
            (r["t_mono"], lat * 1e3)
            for r in batches
            for lat in (r.get("query_latency_s") or ())
            if lat is not None
        )
        out: dict = {}
        for sp in ep_spans:
            t0 = sp.t_start_mono
            t1 = (
                sp.t_end_mono if sp.t_end_mono is not None
                else float("inf")
            )

            def _in(r, t0=t0, t1=t1):
                return t0 <= r.get("t_mono", r.get("t", 0.0)) <= t1

            win = [r for r in batches if _in(r)]
            lats_ms = [
                lat * 1e3
                for r in win
                for lat in (r.get("query_latency_s") or ())
                if lat is not None
            ]
            rows = [row for r in win for row in self._decomp_rows(r)]
            p99_ms = None
            if lats_ms:
                ws = sorted(lats_ms)
                p99_ms = round(
                    ws[min(len(ws) - 1, int(len(ws) * 0.99))], 3
                )
            slo = (
                slo_summary(self.slo_p99_ms, lats_ms, p99_ms=p99_ms)
                if self.slo_p99_ms is not None and lats_ms else None
            )
            decomp = (
                self._decomposition(rows, self._serve_agg, False)
                if rows else None
            )
            fault = bool(sp.attrs.get("fault"))
            recovery_ms = None
            recovered = None
            if fault and self.slo_p99_ms is not None:
                recovery_ms = self._recovery_from(
                    t0, completions, self.slo_p99_ms
                )
                recovered = recovery_ms is not None
            out[sp.name] = {
                "kind": sp.attrs.get("kind"),
                "fault": fault,
                "t_start_s": round(t0 - tracer.t0_mono, 6),
                "duration_s": round(sp.duration_s, 6),
                "requests": len(lats_ms),
                "rejected": sum(r.get("rejected", 0) for r in win),
                "sheds": sum(
                    r.get("dropped", 1) for r in serve_events
                    if r.get("serve") == "shed" and _in(r)
                ),
                "lane_restarts": sum(
                    1 for r in serve_events
                    if r.get("serve") == "lane"
                    and r.get("event") == "restart" and _in(r)
                ),
                "lane_deaths": sum(
                    1 for r in serve_events
                    if r.get("serve") == "lane"
                    and r.get("event") == "dead" and _in(r)
                ),
                "breaker_trips": sum(
                    1 for r in serve_events
                    if r.get("serve") == "breaker"
                    and r.get("event") == "open" and _in(r)
                ),
                "drift_refreshes": sum(
                    1 for r in serve_events
                    if r.get("serve") == "drift" and _in(r)
                ),
                "fleet_requests": sum(
                    r.get("tenants", 0) for r in fleet_buckets
                    if _in(r)
                ),
                "membership_events": sum(
                    1 for r in membership if _in(r)
                ),
                "p99_ms": p99_ms,
                "slo": slo,
                "latency_decomposition": decomp,
                "recovery_ms": recovery_ms,
                "recovered": recovered,
            }
        return out

    def _slo_summary(self, out: dict) -> dict:
        """The ``summary()["slo"]`` section: attainment + error-budget
        burn against the declared p99 targets. The live ring buffers
        are the rolling window; evicted requests count via the
        aggregates (folded with the target in force at eviction
        time)."""
        slo: dict = {}
        if self.slo_p99_ms is not None:
            lats = [
                lat * 1e3
                for r in self.serve_records
                if r.get("serve") == "batch"
                for lat in (r.get("query_latency_s") or ())
                if lat is not None
            ]
            agg = self._serve_agg
            if lats or agg["slo_requests"]:
                p99_s = out.get("serving", {}).get("p99_latency_s")
                slo["serve"] = slo_summary(
                    self.slo_p99_ms,
                    lats,
                    evicted_requests=agg["slo_requests"],
                    evicted_violations=agg["slo_violations"],
                    p99_ms=(
                        round(p99_s * 1e3, 3) if p99_s is not None else None
                    ),
                )
        if self.fleet_slo_p99_ms is not None:
            lats = [
                lat * 1e3
                for r in self.fleet_records
                if r.get("fleet") == "bucket"
                for lat in (r.get("request_latency_s") or ())
                if lat is not None
            ]
            agg = self._fleet_agg
            if lats or agg["slo_requests"]:
                p99_s = out.get("fleet", {}).get("p99_latency_s")
                slo["fleet"] = slo_summary(
                    self.fleet_slo_p99_ms,
                    lats,
                    evicted_requests=agg["slo_requests"],
                    evicted_violations=agg["slo_violations"],
                    p99_ms=(
                        round(p99_s * 1e3, 3) if p99_s is not None else None
                    ),
                )
        return slo


def log_line(msg: str, **fields) -> None:
    """One structured log line to stderr (replaces the reference's
    prints). Carries both clocks like every other event (``time`` stays
    for existing consumers; it is the unix stamp)."""
    rec = {
        "msg": msg,
        "time": time.time(),
        "t_unix": time.time(),
        "t_mono": time.perf_counter(),
        **fields,
    }
    print(json.dumps(rec), file=sys.stderr, flush=True)
