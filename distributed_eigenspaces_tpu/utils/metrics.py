"""Structured metrics & logging (SURVEY.md §5.5).

Replaces the reference's observability — a dozen ``print`` calls
(``distributed.py:35,44,56,92,98,107,114,120,131,137,142``) and one
wall-clock span (``distributed.py:93,131``) — with per-step structured
records: throughput (the BASELINE.json samples/sec metric), step latency,
and optional accuracy (principal angle vs a reference subspace).
"""

from __future__ import annotations

import json
import sys
import time
from typing import IO


class MetricsLogger:
    """Collects per-step metrics; optionally streams them as JSON lines.

    Use as an ``on_step`` callback factory::

        metrics = MetricsLogger(samples_per_step=m * n)
        online_distributed_pca(stream, cfg, on_step=metrics.on_step)
        print(metrics.summary())
    """

    def __init__(
        self,
        *,
        samples_per_step: int = 0,
        stream: IO | None = None,
        reference_subspace=None,
    ):
        self.samples_per_step = samples_per_step
        self.stream = stream
        self.reference_subspace = reference_subspace
        self.records: list[dict] = []
        #: structured fault events (runtime/supervisor.py): quarantined
        #: workers, retried pulls/steps, resumes — the run's fault
        #: ledger, surfaced by :meth:`summary`
        self.fault_records: list[dict] = []
        #: ingest-pipeline counters (runtime/prefetch.py PrefetchStats),
        #: attached via :meth:`attach_ingest` — surfaced by
        #: :meth:`summary` under "ingest"
        self.ingest_stats = None
        #: query-serving events (serving/server.py QueryServer batches,
        #: serving/drift.py DriftMonitor refreshes) — surfaced by
        #: :meth:`summary` under "serving"
        self.serve_records: list[dict] = []
        #: fleet-serving events (parallel/fleet.py FleetServer bucket
        #: dispatches) — surfaced by :meth:`summary` under "fleet"
        self.fleet_records: list[dict] = []
        #: compile-lifecycle counters (utils/compile_cache.py
        #: CompileCache), attached via :meth:`attach_compile` —
        #: surfaced by :meth:`summary` under "compile"
        self.compile_cache = None
        self._last_time = None

    def start(self) -> "MetricsLogger":
        self._last_time = time.perf_counter()
        return self

    def on_step(self, t: int, state, v_bar=None) -> None:
        now = time.perf_counter()
        rec: dict = {"step": int(t)}
        if self._last_time is not None:
            dt = now - self._last_time
            rec["step_seconds"] = round(dt, 6)
            if self.samples_per_step:
                rec["samples_per_sec"] = round(self.samples_per_step / dt, 1)
        if self.reference_subspace is not None and v_bar is not None:
            from distributed_eigenspaces_tpu.ops.linalg import (
                principal_angles_degrees,
            )

            rec["principal_angle_deg"] = round(
                float(
                    principal_angles_degrees(
                        v_bar, self.reference_subspace
                    ).max()
                ),
                4,
            )
        self._last_time = now
        self.records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def attach_ingest(self, stats) -> "MetricsLogger":
        """Attach a live ``runtime.prefetch.PrefetchStats`` — its final
        counters land in ``summary()["ingest"]``, so ingest-bound vs
        compute-bound runs are diagnosable from the run report (the
        counters keep mutating as the stream runs; summary reads the
        state at call time)."""
        self.ingest_stats = stats
        return self

    def attach_compile(self, cache) -> "MetricsLogger":
        """Attach a live ``utils.compile_cache.CompileCache`` — its
        hit/miss/compile-ms counters land in ``summary()["compile"]``
        (read at summary time, like the ingest stats), so cold-start
        cost and cache effectiveness are diagnosable from the run
        report."""
        self.compile_cache = cache
        return self

    def fleet(self, event: dict) -> None:
        """Record one structured fleet-serving event — a dispatched fit
        bucket (``kind="bucket"``: tenant count, occupancy, signature,
        and the per-signature ``compile_stall_ms`` the dispatch paid
        acquiring its programs). Rides the same JSON stream as step
        records, tagged ``"fleet"``."""
        rec = {"fleet": event.get("kind", "bucket"), **event}
        rec.setdefault("t", time.perf_counter())
        self.fleet_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def serve(self, event: dict) -> None:
        """Record one structured serving event — a dispatched query
        micro-batch (``kind="batch"``: query count, per-query
        latencies, occupancy, basis version, swap flag) or a drift
        refresh (``kind="drift"``: score, angle gap, published
        version). Rides the same JSON stream as step records, tagged
        ``"serve"``."""
        rec = {"serve": event.get("kind", "batch"), **event}
        rec.setdefault("t", time.perf_counter())
        self.serve_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def fault(self, event: dict) -> None:
        """Record one structured fault event (a supervisor detection /
        recovery action). Events ride the same JSON stream as step
        records, tagged ``"fault"`` so consumers can split them."""
        rec = {"fault": event.get("kind", "unknown"), **event}
        self.fault_records.append(rec)
        if self.stream is not None:
            print(json.dumps(rec), file=self.stream, flush=True)

    def summary(self) -> dict:
        """Aggregate: total steps, mean/max throughput, final accuracy,
        and — when any fault was recorded — the fault ledger (count,
        per-kind histogram, and the raw events)."""
        out: dict = {"steps": len(self.records)}
        sps = [r["samples_per_sec"] for r in self.records if "samples_per_sec" in r]
        if sps:
            out["mean_samples_per_sec"] = round(sum(sps) / len(sps), 1)
            out["max_samples_per_sec"] = round(max(sps), 1)
        angles = [
            r["principal_angle_deg"]
            for r in self.records
            if "principal_angle_deg" in r
        ]
        if angles:
            out["final_principal_angle_deg"] = angles[-1]
        if self.fault_records:
            by_kind: dict[str, int] = {}
            for r in self.fault_records:
                by_kind[r["fault"]] = by_kind.get(r["fault"], 0) + 1
            out["faults"] = {
                "count": len(self.fault_records),
                "by_kind": by_kind,
                "events": list(self.fault_records),
            }
        if self.ingest_stats is not None:
            out["ingest"] = self.ingest_stats.as_dict()
        if self.serve_records:
            out["serving"] = self._serving_summary()
        if self.fleet_records:
            out["fleet"] = self._fleet_summary()
        if self.compile_cache is not None:
            out["compile"] = self.compile_cache.stats()
        return out

    @staticmethod
    def _stall_fields(records: list[dict]) -> dict:
        """Shared compile-stall aggregation for the serving and fleet
        sections: total misses, total stall ms, and the per-signature
        stall breakdown that makes a p99 regression attributable to
        the exact shape that compiled inline."""
        out: dict = {
            "compile_misses": sum(
                r.get("compile_misses", 0) for r in records
            ),
            "compile_stall_ms": round(
                sum(r.get("compile_stall_ms", 0.0) for r in records), 3
            ),
        }
        by_sig: dict[str, float] = {}
        for r in records:
            stall = r.get("compile_stall_ms", 0.0)
            if stall and "signature" in r:
                sig = str(tuple(r["signature"]))
                by_sig[sig] = round(by_sig.get(sig, 0.0) + stall, 3)
        if by_sig:
            out["compile_stall_ms_by_signature"] = by_sig
        return out

    def _fleet_summary(self) -> dict:
        """The ``summary()["fleet"]`` section (mirrors ``["serving"]``):
        dispatched buckets, tenants served, mean bucket occupancy, and
        the compile-stall ledger."""
        buckets = [
            r for r in self.fleet_records if r["fleet"] == "bucket"
        ]
        out: dict = {"buckets": len(buckets)}
        if buckets:
            out["tenants"] = sum(r.get("tenants", 0) for r in buckets)
            occ = [r["occupancy"] for r in buckets if "occupancy" in r]
            if occ:
                out["mean_occupancy"] = round(sum(occ) / len(occ), 4)
            out.update(self._stall_fields(buckets))
        return out

    def _serving_summary(self) -> dict:
        """The ``summary()["serving"]`` section (mirrors ``["ingest"]``):
        qps over the served window, p50/p99 query latency, mean batch
        occupancy, hot-swap count, and the latest drift score."""
        batches = [r for r in self.serve_records if r["serve"] == "batch"]
        out: dict = {"batches": len(batches)}
        if batches:
            queries = sum(r.get("queries", 0) for r in batches)
            out["queries"] = queries
            out["rejected"] = sum(r.get("rejected", 0) for r in batches)
            ts = [r["t"] for r in batches]
            span = max(ts) - min(ts)
            if len(batches) > 1 and span > 0:
                # arrival-window rate; a single batch has no window, so
                # its own dispatch time is the only honest denominator
                out["qps"] = round(queries / span, 1)
            else:
                secs = sum(r.get("batch_seconds", 0.0) for r in batches)
                if secs > 0:
                    out["qps"] = round(queries / secs, 1)
            lat = sorted(
                l for r in batches for l in r.get("query_latency_s", ())
            )
            if lat:
                out["p50_latency_s"] = round(
                    lat[len(lat) // 2], 6
                )
                out["p99_latency_s"] = round(
                    lat[min(len(lat) - 1, int(len(lat) * 0.99))], 6
                )
            occ = [r["occupancy"] for r in batches if "occupancy" in r]
            if occ:
                out["mean_occupancy"] = round(sum(occ) / len(occ), 4)
            out["swaps"] = sum(1 for r in batches if r.get("swap"))
            versions = {r["version"] for r in batches if "version" in r}
            out["versions_served"] = sorted(versions)
            out.update(self._stall_fields(batches))
        drifts = [r for r in self.serve_records if r["serve"] == "drift"]
        if drifts:
            out["drift_refreshes"] = len(drifts)
            out["drift_score"] = drifts[-1].get("score")
            out["drift_published"] = [
                r["published"] for r in drifts
                if r.get("published") is not None
            ]
        return out


def log_line(msg: str, **fields) -> None:
    """One structured log line to stderr (replaces the reference's prints)."""
    rec = {"msg": msg, "time": time.time(), **fields}
    print(json.dumps(rec), file=sys.stderr, flush=True)
