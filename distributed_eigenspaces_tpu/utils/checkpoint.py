"""Checkpoint / resume of the online PCA state (SURVEY.md §5.4).

The reference keeps everything in process memory — ``sigma_tilde``,
``computed_eigens`` and the remaining batch list all die with the master
process (``distributed.py:88-91``; notebook cell 16 locals). Here the
complete resumable state is tiny and explicit:

  - dense path:      ``OnlineState``   = sigma_tilde (d, d) + step
  - low-rank path:   ``LowRankState``  = U (d, r) + S (r,) + step
  - segmented scan:  ``SegmentState``  = OnlineState + the warm carry
    ``v_prev`` (d, k), so a resumed scan run is bit-for-bit the unkilled run
  - plus the data-stream cursor (an integer row offset)

Storage is a plain ``state.npz`` plus an atomically-renamed ``meta.json``
commit marker (a crash mid-write leaves no meta.json, so the checkpoint is
simply not found). Since ISSUE 8 the marker also carries a sha256 of the
payload, and :meth:`Checkpointer.latest` is a RESUME LADDER: a committed
checkpoint whose payload is torn or checksum-bad is quarantined loudly
(renamed ``*.quarantined`` — evidence kept, the PR 7 registry
discipline) and the ladder steps back to the newest checkpoint that
actually restores, instead of failing the resume on damaged bytes. The
payload is gathered to host on save, so restore works on any topology —
state saved from an 8-device mesh restores onto 1 device or 64. States
are a few d*r floats; orbax's async machinery buys nothing at this size.

Sharded leaves (ISSUE 15): a state whose leaves carry a
``NamedSharding`` (the feature-sharded trainers' carries — ``U`` rows
over the ``features`` mesh axis) records each leaf's PartitionSpec in
the commit marker, and :func:`restore_checkpoint` with a ``mesh`` puts
every leaf straight back onto its recorded spec — the host array
transfers per shard, so a ``(d, r)`` carry resumes on the mesh without
a dense single-device stop. Restore without a mesh keeps the old
behavior (host -> default placement), so dense-topology resumes are
untouched.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any

import jax
import numpy as np

from distributed_eigenspaces_tpu.utils.metrics import log_line


class CheckpointCorrupt(RuntimeError):
    """A COMMITTED checkpoint whose payload does not restore: torn /
    truncated npz, checksum mismatch, or missing fields. Distinct from
    "no committed checkpoint" (FileNotFoundError): the marker landed
    but the bytes are damaged — disk rot, tamper, or a partial copy."""

from distributed_eigenspaces_tpu.algo.online import OnlineState
from distributed_eigenspaces_tpu.algo.scan import SegmentState
from distributed_eigenspaces_tpu.parallel.feature_sharded import (
    LowRankState,
    SketchState,
)

_STATE_TYPES = {
    "online": OnlineState,
    "lowrank": LowRankState,
    "scan_segment": SegmentState,
    "sketch": SketchState,
}


def _leaf_spec(x):
    """A leaf's PartitionSpec as JSON (list of axis names; nested
    lists for multi-axis dims), or None for unsharded / non-NamedSharding
    leaves. Captured BEFORE the host gather, which erases it."""
    spec = getattr(getattr(x, "sharding", None), "spec", None)
    if spec is None:
        return None
    out = []
    for ax in tuple(spec):
        out.append(list(ax) if isinstance(ax, tuple) else ax)
    return out


def _to_host(tree):
    """Fully materialize on host (gathers sharded leaves).

    Multi-host: a leaf sharded across processes is not fully addressable,
    so it is gathered with a COLLECTIVE (``process_allgather``) — every
    process must therefore reach ``save_checkpoint`` together (the same
    SPMD discipline as the training step itself); single-process leaves
    take the plain device_get path."""

    def get(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True)
            )
        return np.asarray(jax.device_get(x))

    return jax.tree.map(get, tree)


def save_checkpoint(
    path: str,
    state: OnlineState | LowRankState,
    *,
    cursor: int = 0,
    extra: dict[str, Any] | None = None,
) -> None:
    """Write a self-describing checkpoint directory at ``path``.

    Multi-host: call from EVERY process (the sharded-state gather is a
    collective); only process 0 touches the filesystem, so a shared
    checkpoint directory sees exactly one writer. Restore+device_put
    with the trainer's ``state_shardings`` re-shards on any topology.
    """
    kind = next(
        (n for n, cls in _STATE_TYPES.items() if isinstance(state, cls)),
        None,
    )
    if kind is None:
        raise ValueError(
            f"unsupported checkpoint state type {type(state).__name__}; "
            f"known: {sorted(_STATE_TYPES)}"
        )
    # leaf PartitionSpecs, recorded before the gather erases them —
    # restore_checkpoint(mesh=...) re-places each leaf onto its spec
    leaf_specs = {f: _leaf_spec(getattr(state, f)) for f in state._fields}
    host = _to_host(state)  # collective — before any process-0 gate
    multi = jax.process_count() > 1
    if not multi or jax.process_index() == 0:
        _write_checkpoint(path, host, kind, cursor, extra, leaf_specs)
    if multi:
        # barrier AFTER the commit marker: without it a non-zero process
        # returning early could restore (or assert existence) before
        # process 0 finished writing — a flaky missing-checkpoint race
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("det_ckpt_commit")


def _write_checkpoint(path, host, kind, cursor, extra, leaf_specs=None):
    os.makedirs(path, exist_ok=True)
    # Invalidate any previous commit marker BEFORE touching state.npz, and
    # write the payload via tmp+rename: a crash at any point leaves either
    # the old complete checkpoint (marker still present, payload untouched)
    # or no committed checkpoint — never a committed-but-corrupt one.
    meta_final = os.path.join(path, "meta.json")
    if os.path.exists(meta_final):
        os.remove(meta_final)
    # tmp name must keep the .npz suffix (np.savez appends it otherwise)
    state_tmp = os.path.join(path, "state.tmp.npz")
    np.savez(state_tmp, **{f: getattr(host, f) for f in host._fields})
    with open(state_tmp, "rb") as f:
        checksum = hashlib.sha256(f.read()).hexdigest()
    os.replace(state_tmp, os.path.join(path, "state.npz"))
    meta = {
        "state_type": kind,
        "cursor": int(cursor),
        "step": int(host.step),
        "format_version": 1,
        # payload sha256: lets restore tell torn/rotted bytes from a
        # valid commit (ISSUE 8 resume ladder; absent on older
        # checkpoints — those restore unverified, back-compat)
        "checksum": checksum,
    }
    if leaf_specs and any(s is not None for s in leaf_specs.values()):
        # per-leaf PartitionSpecs (None = unsharded leaf): the sharded
        # round-trip half of the marker — absent on dense checkpoints
        # and on anything written before ISSUE 15 (those restore to the
        # default placement, as ever)
        meta["leaf_specs"] = leaf_specs
    if extra:
        meta["extra"] = extra
    tmp = os.path.join(path, "meta.json.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, meta_final)  # atomic commit marker


def restore_checkpoint(path: str, *, mesh=None):
    """Load ``(state, cursor)`` from a checkpoint directory.

    Raises FileNotFoundError on a missing/uncommitted checkpoint (a crash
    between state.npz and meta.json leaves no meta.json — the write is
    treated as never having happened).

    ``mesh``: re-place every leaf whose PartitionSpec the marker
    recorded (sharded trainers' carries) with
    ``NamedSharding(mesh, spec)`` — host bytes transfer per shard, the
    carry resumes on-mesh without a dense single-device stop. Leaves
    without a recorded spec (and all leaves when ``mesh`` is None) take
    the default placement.
    """
    meta_path = os.path.join(path, "meta.json")
    if not os.path.exists(meta_path):
        raise FileNotFoundError(f"no committed checkpoint at {path!r}")
    with open(meta_path) as f:
        meta = json.load(f)
    cls = _STATE_TYPES[meta["state_type"]]
    payload = os.path.join(path, "state.npz")
    want = meta.get("checksum")
    if want is not None:
        try:
            with open(payload, "rb") as f:
                got = hashlib.sha256(f.read()).hexdigest()
        except OSError as e:
            raise CheckpointCorrupt(
                f"committed checkpoint at {path!r} has an unreadable "
                f"payload: {e!r}"
            ) from e
        if got != want:
            raise CheckpointCorrupt(
                f"committed checkpoint at {path!r} failed its payload "
                f"checksum (sha256 {got[:12]}… != recorded "
                f"{want[:12]}…): torn or rotted bytes"
            )
    leaf_specs = meta.get("leaf_specs") or {}

    def _place(name, arr):
        import jax.numpy as jnp

        spec = leaf_specs.get(name)
        if mesh is None or spec is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        axes = tuple(
            tuple(ax) if isinstance(ax, list) else ax for ax in spec
        )
        return jax.device_put(arr, NamedSharding(mesh, P(*axes)))

    try:
        with np.load(payload) as z:
            state = cls(**{f: _place(f, z[f]) for f in cls._fields})
    except FileNotFoundError:
        raise
    except Exception as e:  # torn zip, missing field, bad dtype...
        raise CheckpointCorrupt(
            f"committed checkpoint at {path!r} does not restore: {e!r}"
        ) from e
    return state, meta["cursor"]


@dataclasses.dataclass
class Checkpointer:
    """Periodic checkpoint hook for the online loop.

    Use as the ``on_step`` callback::

        ckpt = Checkpointer("/path/ckpt", every=5)
        online_distributed_pca(stream, cfg, on_step=ckpt.on_step)

    Keeps the latest ``keep`` checkpoints as ``step_{t:08d}`` subdirs.
    """

    directory: str
    every: int = 1
    keep: int = 2
    rows_per_step: int = 0  # rows consumed per step -> saved stream cursor
    #: optional mesh for sharded-carry resumes: latest() re-places each
    #: leaf onto its recorded PartitionSpec (restore_checkpoint docs)
    mesh: Any = None

    def on_step(self, t: int, state, v_bar=None) -> None:
        if t % self.every:
            return
        path = os.path.join(self.directory, f"step_{t:08d}")
        save_checkpoint(path, state, cursor=t * self.rows_per_step)
        self._gc()

    def latest(self):
        """Restore the newest committed checkpoint that actually
        RESTORES, or None — the resume ladder (ISSUE 8): a committed
        step whose payload is torn or checksum-bad is quarantined
        loudly (directory renamed ``*.quarantined`` — evidence kept,
        never silently deleted) and the ladder steps back to the next
        newest, so one rotted file degrades the resume by a few steps
        instead of failing it."""
        for step in reversed(self._steps()):
            path = os.path.join(self.directory, f"step_{step:08d}")
            try:
                return restore_checkpoint(path, mesh=self.mesh)
            except CheckpointCorrupt as e:
                quarantined = path + ".quarantined"
                try:
                    os.replace(path, quarantined)
                except OSError:
                    quarantined = None
                log_line(
                    "checkpoint quarantined: stepping the resume "
                    "ladder back",
                    step=step, error=str(e), quarantined=quarantined,
                )
            except FileNotFoundError:
                continue  # lost a GC race — older steps still stand
        return None

    def _steps(self):
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            # "step_NNNNNNNN" only — quarantined dirs keep the prefix
            # but grow a suffix, and must never re-enter the ladder
            if name.startswith("step_") and name[5:].isdigit():
                if os.path.exists(
                    os.path.join(self.directory, name, "meta.json")
                ):
                    out.append(int(name[5:]))
        return sorted(out)

    def _gc(self):
        # single-writer discipline, like save_checkpoint's: in a
        # multi-host run every process calls on_step against the SHARED
        # directory, and concurrent rmtree of the same step dirs was
        # only masked by ignore_errors — worse, a non-zero process
        # could delete a checkpoint process 0 is concurrently reading
        # via latest(). Process 0 (the writer) is the only collector.
        # (save_checkpoint barriers after its commit marker, so by the
        # time any process returns from on_step the new checkpoint is
        # durable and collecting old ones is safe.)
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        steps = self._steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            import shutil

            shutil.rmtree(
                os.path.join(self.directory, f"step_{s:08d}"),
                ignore_errors=True,
            )
