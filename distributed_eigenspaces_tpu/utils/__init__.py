"""Auxiliary subsystems (SURVEY.md §5): checkpointing, metrics, tracing,
fault injection. The reference had none of these — its only observability was
print statements and one wall-clock span (``distributed.py:93,131``), and its
only fault story was AMQP at-least-once redelivery (``distributed.py:53``).
"""

from distributed_eigenspaces_tpu.utils.checkpoint import (
    save_checkpoint,
    restore_checkpoint,
    Checkpointer,
)
from distributed_eigenspaces_tpu.utils.metrics import MetricsLogger
from distributed_eigenspaces_tpu.utils.faults import FaultInjector
from distributed_eigenspaces_tpu.utils.guards import checked_jit, checks_enabled
from distributed_eigenspaces_tpu.utils.telemetry import Histogram, Tracer
from distributed_eigenspaces_tpu.utils.tracing import named_scope, profile_to

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "Checkpointer",
    "MetricsLogger",
    "FaultInjector",
    "Histogram",
    "Tracer",
    "checked_jit",
    "checks_enabled",
    "named_scope",
    "profile_to",
]
