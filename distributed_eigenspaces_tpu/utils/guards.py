"""NaN/inf guards — the sanitizer half of SURVEY.md §5.2.

Races are ruled out by construction in this framework (pure jitted steps;
no shared mutable state), so the remaining hazard class the survey's
race/sanitizer plan names is SILENT numerical corruption: a bf16 overflow,
a degenerate Cholesky, or a zero-norm basis propagating NaN through the
online state with no error anywhere (the reference would print garbage
just as silently — it has no guards at all).

``DET_CHECKIFY=1`` arms ``jax.experimental.checkify`` float checks on the
training steps: the first NaN/inf raised BY ANY PRIMITIVE inside the
step fails loudly with the offending location, instead of corrupting
``sigma_tilde`` quietly. Off by default (the checks instrument every op
— debug tool, not a production mode). Resolved at trainer BUILD time,
same contract as ``DET_NO_PALLAS`` (an env read under jit would be frozen
by the trace cache anyway).

In guarded mode ``out_shardings``/donation are dropped from the jit
(checkify changes the output pytree to (error, out)); fine for a debug
mode.
"""

from __future__ import annotations

import os

import jax


def checks_enabled(explicit: bool | None = None) -> bool:
    """Build-time resolution of the NaN-guard switch: an explicit value
    wins, else the ``DET_CHECKIFY`` env var."""
    if explicit is not None:
        return explicit
    return os.environ.get("DET_CHECKIFY", "0") == "1"


def checked_jit(fn, *, enabled: bool | None = None, **jit_kwargs):
    """``jax.jit(fn, **jit_kwargs)``, or the checkified equivalent when
    NaN guards are armed: returns a callable with ``fn``'s signature that
    raises ``checkify.JaxRuntimeError`` on the first NaN/inf produced
    inside the step."""
    if not checks_enabled(enabled):
        return jax.jit(fn, **jit_kwargs)
    from jax.experimental import checkify

    jit_kwargs.pop("out_shardings", None)
    jit_kwargs.pop("donate_argnums", None)
    # float_checks: NaN/inf from any primitive. user_checks: explicit
    # checkify.check() sites (e.g. the ns_orth orthonormality residual)
    # that guard conditions float checks can't see.
    cf = jax.jit(
        checkify.checkify(
            fn, errors=checkify.float_checks | checkify.user_checks
        ),
        **jit_kwargs,
    )

    def wrapped(*args, **kw):
        err, out = cf(*args, **kw)
        checkify.check_error(err)
        return out

    return wrapped
