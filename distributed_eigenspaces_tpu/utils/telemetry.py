"""Unified telemetry: request-scoped spans, one merged timeline (ISSUE 6).

The system spans five subsystems (supervised fit, fleet dispatch, query
serving, drift refits, compile prewarm), and before this module its
observability was a bag of per-subsystem event lists: a p99 regression
showed up as one number with no way to tell queue wait from device
compute from compile stall. The TPU linear-algebra playbook
(arXiv:2112.09017) treats profiling attribution as a first-class part of
scaling dense kernels; this is the instrumentation layer the ROADMAP's
hierarchical-merge and tail-latency items land on.

Three primitives, deliberately host-side-cheap (a lock, a counter, an
append — never device work):

- :class:`Tracer` — nested, correlation-ID'd spans. Every request
  ticket / fit run / drift arc gets a ``trace_id``; spans carry
  parent-child links plus BOTH clocks (``time.perf_counter`` for
  ordering/durations, ``time.time`` for cross-process correlation).
  :meth:`Tracer.export_chrome_trace` writes a Chrome trace-event JSON
  that Perfetto / ``chrome://tracing`` load directly, so host spans
  from every subsystem land on ONE timeline. Spans opened with
  ``device=True`` additionally enter a ``jax.profiler.TraceAnnotation``
  (``utils/tracing.py``), so when a ``jax.profiler`` capture runs
  alongside, the same names annotate the device timeline — the
  host/device merge point.
- :class:`Histogram` — bounded log-spaced latency buckets, mergeable,
  with geometric-interpolated quantile estimates. Replaces unbounded
  raw-latency lists: ``MetricsLogger``'s ring buffers fold evicted
  events into these, so a long-lived server's ``summary()`` stays
  correct at O(buckets) memory.
- :func:`slo_summary` — rolling-window SLO attainment + error-budget
  burn for a declared p99 target (``cfg.serve_slo_p99_ms`` /
  ``cfg.fleet_slo_p99_ms``), surfaced as ``summary()["slo"]``.

Cross-thread propagation rule (docs/OBSERVABILITY.md): a trace is born
where the request enters the system (``submit``); its ``trace_id`` rides
the ticket payload to the dispatch lane, which records the queue/compute
spans AFTER the fact with :meth:`Tracer.record_span` — spans never
require the opening and closing thread to match.

Every entry point is null-safe via :func:`tracer_of` /
:data:`NULL_TRACER`: instrumented code calls ``tracer_of(metrics)`` and
traces unconditionally; with no tracer attached the calls are no-ops of
a few attribute lookups.
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Any, Iterator

__all__ = [
    "Histogram",
    "NULL_TRACER",
    "NullTracer",
    "RingLog",
    "Span",
    "Tracer",
    "slo_summary",
    "tracer_of",
]


# -- spans -------------------------------------------------------------------


class Span:
    """One finished (or open) span. Host-side record only — creation is
    a few attribute writes; the device sees nothing unless the span was
    opened with ``device=True``."""

    __slots__ = (
        "name", "category", "trace_id", "span_id", "parent_id",
        "t_start_mono", "t_end_mono", "t_start_unix", "attrs",
        "thread_id", "phase",
    )

    def __init__(
        self,
        name: str,
        *,
        category: str = "host",
        trace_id: str | None = None,
        span_id: int = 0,
        parent_id: int | None = None,
        t_start_mono: float = 0.0,
        t_end_mono: float | None = None,
        t_start_unix: float = 0.0,
        attrs: dict | None = None,
        thread_id: int = 0,
        phase: str = "X",
    ):
        self.name = name
        self.category = category
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t_start_mono = t_start_mono
        self.t_end_mono = t_end_mono
        self.t_start_unix = t_start_unix
        self.attrs = attrs or {}
        self.thread_id = thread_id
        self.phase = phase

    @property
    def duration_s(self) -> float:
        if self.t_end_mono is None:
            return 0.0
        return self.t_end_mono - self.t_start_mono

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "cat": self.category,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "t_mono": self.t_start_mono,
            "t_unix": self.t_start_unix,
            "duration_s": round(self.duration_s, 9),
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class _SpanHandle:
    """Context manager for an in-flight span; closes it on exit.

    ``handle.span_id`` / ``handle.trace_id`` are readable inside the
    ``with`` body for explicit child parenting across threads."""

    __slots__ = ("_tracer", "span", "_device_cm")

    def __init__(self, tracer: "Tracer", span: Span, device_cm=None):
        self._tracer = tracer
        self.span = span
        self._device_cm = device_cm

    @property
    def trace_id(self) -> str | None:
        return self.span.trace_id

    @property
    def span_id(self) -> int:
        return self.span.span_id

    def set(self, **attrs) -> "_SpanHandle":
        """Attach attributes to the span while it is open."""
        self.span.attrs.update(attrs)
        return self

    def __enter__(self) -> "_SpanHandle":
        if self._device_cm is not None:
            self._device_cm.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        if self._device_cm is not None:
            self._device_cm.__exit__(*exc)
        self._tracer._close(self.span)


class _EpisodeHandle:
    """An open scenario episode (:meth:`Tracer.episode`): closing it
    records ONE ``category="episode"`` span covering the open interval.

    Deliberately OFF the per-thread implicit stack — episodes overlap
    each other and outlive the thread that opened them, so they must
    never parent (or be parented by) request spans. The export routes
    them to their own top-level track."""

    __slots__ = ("_tracer", "name", "attrs", "t_start_mono",
                 "t_start_unix", "span_id", "_closed")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.t_start_mono = time.perf_counter()
        self.t_start_unix = time.time()
        self.span_id: int | None = None
        self._closed = False

    def set(self, **attrs) -> "_EpisodeHandle":
        self.attrs.update(attrs)
        return self

    def close(self) -> int | None:
        """Record the episode span; idempotent. Returns the span id."""
        if self._closed:
            return self.span_id
        self._closed = True
        self.span_id = self._tracer.record_span(
            self.name,
            self.t_start_mono,
            time.perf_counter(),
            category="episode",
            attrs=self.attrs,
            t_start_unix=self.t_start_unix,
            thread_id=0,
        )
        return self.span_id

    def __enter__(self) -> "_EpisodeHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Tracer:
    """Thread-safe span collector with a bounded buffer.

    Spans nest implicitly per thread (a ``span()`` opened inside
    another's ``with`` body parents to it) and explicitly across
    threads (``parent=`` / ``trace_id=`` carried on the ticket).
    ``max_spans`` bounds memory on long-lived servers; evicted spans
    bump :attr:`dropped` so a truncated export is loud, not silent.
    """

    def __init__(self, *, max_spans: int = 65536):
        if max_spans < 1:
            raise ValueError(f"max_spans must be >= 1: {max_spans}")
        self.max_spans = max_spans
        self.enabled = True
        self.spans: list[Span] = []
        self.dropped = 0
        self._lock = threading.Lock()
        self._next_span = 1
        self._next_trace = 1
        self._local = threading.local()
        # one clock anchor pair for the whole tracer: exports place
        # every span on the monotonic axis and carry the unix anchor so
        # two processes' traces can be shifted onto one wall clock
        self.t0_mono = time.perf_counter()
        self.t0_unix = time.time()

    # -- ids -----------------------------------------------------------------

    def new_trace(self, kind: str = "trace") -> str:
        """A fresh correlation id: one per request ticket / fit run /
        drift arc. Process-qualified so merged multi-process streams
        never collide."""
        with self._lock:
            n = self._next_trace
            self._next_trace += 1
        return f"{kind}-{os.getpid():x}-{n:06x}"

    def _alloc(self) -> int:
        with self._lock:
            n = self._next_span
            self._next_span += 1
        return n

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> Span | None:
        """The innermost open span on THIS thread (implicit parent)."""
        st = self._stack()
        return st[-1] if st else None

    # -- recording -----------------------------------------------------------

    def span(
        self,
        name: str,
        *,
        trace_id: str | None = None,
        parent: int | None = None,
        category: str = "host",
        attrs: dict | None = None,
        device: bool = False,
    ) -> _SpanHandle:
        """Open a span; use as a context manager. Inherits ``trace_id``
        and parent from the enclosing span on this thread when not
        given. ``device=True`` additionally enters a
        ``jax.profiler.TraceAnnotation`` so the name shows up on the
        device profiler timeline (the merge with ``named_scope`` /
        ``StepTraceAnnotation`` regions)."""
        cur = self.current()
        if trace_id is None and cur is not None:
            trace_id = cur.trace_id
        if parent is None and cur is not None:
            parent = cur.span_id
        sp = Span(
            name,
            category=category,
            trace_id=trace_id,
            span_id=self._alloc(),
            parent_id=parent,
            t_start_mono=time.perf_counter(),
            t_start_unix=time.time(),
            attrs=dict(attrs) if attrs else {},
            thread_id=threading.get_ident(),
        )
        device_cm = None
        if device:
            device_cm = _device_annotation(name)
        self._stack().append(sp)
        return _SpanHandle(self, sp, device_cm)

    def _close(self, sp: Span) -> None:
        sp.t_end_mono = time.perf_counter()
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        elif sp in st:  # exited out of order — tolerate, don't corrupt
            st.remove(sp)
        self._append(sp)

    def record_span(
        self,
        name: str,
        t_start_mono: float,
        t_end_mono: float,
        *,
        trace_id: str | None = None,
        parent: int | None = None,
        category: str = "host",
        attrs: dict | None = None,
        t_start_unix: float | None = None,
        thread_id: int | None = None,
    ) -> int:
        """Record a span AFTER the fact from explicit timestamps — the
        cross-thread form (queue wait measured on the dispatch lane from
        the submit thread's stamp). Returns the span id for parenting
        children. Timestamps are ``time.perf_counter()`` values."""
        if t_start_unix is None:
            # derive the wall clock from the shared anchor so both
            # clocks stay consistent for spans stamped mono-only
            t_start_unix = self.t0_unix + (t_start_mono - self.t0_mono)
        sp = Span(
            name,
            category=category,
            trace_id=trace_id,
            span_id=self._alloc(),
            parent_id=parent,
            t_start_mono=t_start_mono,
            t_end_mono=t_end_mono,
            t_start_unix=t_start_unix,
            attrs=dict(attrs) if attrs else {},
            thread_id=(
                thread_id if thread_id is not None
                else threading.get_ident()
            ),
        )
        self._append(sp)
        return sp.span_id

    def episode(self, name: str, **attrs) -> _EpisodeHandle:
        """Open a named scenario episode (ISSUE 11): a long span that
        overlaps other episodes and request spans freely, rendered as
        its own top-level track by :meth:`export_chrome_trace`.
        ``MetricsLogger.summary()["episodes"]`` slices per-tier records
        by these spans' windows — the markers ARE the verdict's
        episode boundaries. Close via the returned handle (or use it
        as a context manager)."""
        return _EpisodeHandle(self, name, dict(attrs))

    def event(
        self,
        name: str,
        *,
        trace_id: str | None = None,
        category: str = "host",
        attrs: dict | None = None,
    ) -> None:
        """Record an instant event (zero-duration mark): fault
        detections, cache hits, publishes."""
        cur = self.current()
        if trace_id is None and cur is not None:
            trace_id = cur.trace_id
        now = time.perf_counter()
        sp = Span(
            name,
            category=category,
            trace_id=trace_id,
            span_id=self._alloc(),
            parent_id=cur.span_id if cur is not None else None,
            t_start_mono=now,
            t_end_mono=now,
            t_start_unix=time.time(),
            attrs=dict(attrs) if attrs else {},
            thread_id=threading.get_ident(),
            phase="i",
        )
        self._append(sp)

    def _append(self, sp: Span) -> None:
        with self._lock:
            if len(self.spans) >= self.max_spans:
                # drop oldest: the tail of a long run is what you came
                # to look at; the drop is counted, never silent
                del self.spans[0 : max(1, self.max_spans // 16)]
                self.dropped += max(1, self.max_spans // 16)
            self.spans.append(sp)

    # -- export --------------------------------------------------------------

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self.spans)

    def export_chrome_trace(self, path: str) -> str:
        """Write the merged timeline as Chrome trace-event JSON —
        loadable by Perfetto (ui.perfetto.dev) and ``chrome://tracing``.

        One duration event (``ph: "X"``) per span, on its recording
        thread's track; instant events as ``ph: "i"``. ``args`` carries
        ``trace_id`` / ``parent_id`` / ``t_unix`` plus the span attrs,
        so every served query's chain is correlatable by one id across
        threads. ``otherData`` records the clock anchors and the drop
        count."""
        spans = self.snapshot()
        pid = os.getpid()
        events: list[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": "distributed_eigenspaces_tpu"},
            }
        ]
        # scenario episodes get the top-level track (tid 0, named),
        # above every per-thread track — Perfetto then shows the
        # request spans of each phase directly under its episode bar
        if any(sp.category == "episode" for sp in spans):
            events.append({
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "args": {"name": "episodes"},
            })
        tids = sorted({
            sp.thread_id for sp in spans if sp.category != "episode"
        })
        # compress real thread idents to small track numbers
        tid_map = {t: i + 1 for i, t in enumerate(tids)}
        for t, small in tid_map.items():
            events.append({
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": small,
                "args": {"name": f"thread-{small} ({t})"},
            })
        for sp in spans:
            ev: dict = {
                "name": sp.name,
                "cat": sp.category,
                "ph": sp.phase,
                "ts": round((sp.t_start_mono - self.t0_mono) * 1e6, 3),
                "pid": pid,
                "tid": (
                    0 if sp.category == "episode"
                    else tid_map.get(sp.thread_id, 0)
                ),
                "args": {
                    "trace_id": sp.trace_id,
                    "span_id": sp.span_id,
                    "parent_id": sp.parent_id,
                    "t_unix": round(sp.t_start_unix, 6),
                    **sp.attrs,
                },
            }
            if sp.phase == "X":
                ev["dur"] = round(sp.duration_s * 1e6, 3)
            else:
                ev["s"] = "t"
            events.append(ev)
        doc = {
            "displayTimeUnit": "ms",
            "traceEvents": events,
            "otherData": {
                "t0_unix": self.t0_unix,
                "t0_mono": self.t0_mono,
                "dropped_spans": self.dropped,
                "span_count": len(spans),
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


def _device_annotation(name: str):
    """A ``jax.profiler.TraceAnnotation`` for ``name`` (host annotation
    that shows on the jax profiler's device-correlated timeline), or
    None when jax / the profiler API is unavailable — telemetry must
    never make jax a hard dependency of host-side metrics."""
    try:
        from distributed_eigenspaces_tpu.utils.tracing import (
            trace_annotation,
        )

        return trace_annotation(name)
    except Exception:
        return None


class NullTracer:
    """API-compatible no-op tracer: instrumented code traces
    unconditionally; without a tracer attached every call is a couple
    of attribute lookups and no allocation of span records."""

    enabled = False
    dropped = 0
    spans: list = []

    class _NullHandle:
        trace_id = None
        span_id = None

        def set(self, **attrs):
            return self

        def close(self):
            return None

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return None

    _HANDLE = _NullHandle()

    def new_trace(self, kind: str = "trace") -> None:
        return None

    def current(self) -> None:
        return None

    def span(self, name, **kw) -> "_NullHandle":
        return self._HANDLE

    def episode(self, name, **kw) -> "_NullHandle":
        return self._HANDLE

    def record_span(self, name, t_start_mono, t_end_mono, **kw) -> None:
        return None

    def event(self, name, **kw) -> None:
        return None

    def snapshot(self) -> list:
        return []

    def export_chrome_trace(self, path: str) -> str:
        raise RuntimeError(
            "no tracer attached: construct a telemetry.Tracer and "
            "attach it (MetricsLogger.attach_tracer) before exporting"
        )


NULL_TRACER = NullTracer()


def tracer_of(metrics) -> Any:
    """The tracer attached to a ``MetricsLogger`` (or anything with a
    ``.tracer``), else :data:`NULL_TRACER` — the one null-safety rule
    every instrumentation site uses."""
    tr = getattr(metrics, "tracer", None)
    return tr if tr is not None else NULL_TRACER


# -- histogram ---------------------------------------------------------------


class Histogram:
    """Bounded log-spaced histogram with mergeable counts and quantile
    estimates — the fixed-memory replacement for raw latency lists.

    Bucket upper edges are ``lo * growth**i`` up to ``hi`` plus one
    overflow bucket, so the whole structure is ~60 ints regardless of
    how many values were recorded. Quantiles interpolate geometrically
    inside the winning bucket: the estimate is within one ``growth``
    factor of the exact quantile by construction (tested against known
    distributions). Two histograms with the same parameters merge by
    adding counts — the property that makes ring-buffer eviction safe
    (evicted events fold here; ``summary()`` merges live + evicted).
    """

    __slots__ = ("lo", "hi", "growth", "bounds", "counts", "count",
                 "total", "min", "max")

    def __init__(self, *, lo: float = 1e-6, hi: float = 3600.0,
                 growth: float = 1.5):
        if not (lo > 0 and hi > lo and growth > 1):
            raise ValueError(
                f"need 0 < lo < hi and growth > 1: {lo}, {hi}, {growth}"
            )
        self.lo = lo
        self.hi = hi
        self.growth = growth
        bounds = []
        edge = lo
        while edge < hi:
            bounds.append(edge)
            edge *= growth
        bounds.append(edge)
        self.bounds = bounds  # upper edges; +1 overflow bucket beyond
        self.counts = [0] * (len(bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None

    def record(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        self.counts[i] += 1
        self.count += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def record_many(self, values) -> None:
        for v in values:
            self.record(v)

    def merge(self, other: "Histogram") -> "Histogram":
        if (self.lo, self.hi, self.growth) != (
            other.lo, other.hi, other.growth
        ):
            raise ValueError(
                "cannot merge histograms with different bucket layouts"
            )
        for i, c in enumerate(other.counts):
            self.counts[i] += c
        self.count += other.count
        self.total += other.total
        for m, pick in (("min", min), ("max", max)):
            ov = getattr(other, m)
            if ov is not None:
                sv = getattr(self, m)
                setattr(self, m, ov if sv is None else pick(sv, ov))
        return self

    def copy(self) -> "Histogram":
        h = Histogram(lo=self.lo, hi=self.hi, growth=self.growth)
        h.merge(self)
        return h

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0 <= q <= 1), or None when empty.
        Geometric interpolation inside the winning bucket; clamped to
        the observed min/max so the estimate never leaves the data's
        range."""
        if self.count == 0:
            return None
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q must be in [0, 1]: {q}")
        # nearest-rank target (1-based), matching sorted()[ceil(q*n)-1]
        target = max(1, int(q * self.count + 0.9999999999))
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                if i >= len(self.bounds):  # overflow bucket
                    est = self.max if self.max is not None else self.hi
                else:
                    upper = self.bounds[i]
                    lower = upper / self.growth if i > 0 else 0.0
                    # geometric midpoint-ish: position of the target
                    # rank inside the bucket, interpolated in log space
                    frac = (target - (seen - c)) / max(c, 1)
                    if lower <= 0:
                        est = upper * frac
                    else:
                        est = lower * (upper / lower) ** frac
                lo_clamp = self.min if self.min is not None else est
                hi_clamp = self.max if self.max is not None else est
                return min(max(est, lo_clamp), hi_clamp)
        return self.max

    @property
    def mean(self) -> float | None:
        return self.total / self.count if self.count else None

    def as_dict(self) -> dict:
        out = {
            "count": self.count,
            "sum": round(self.total, 6),
        }
        if self.count:
            out["mean"] = round(self.total / self.count, 9)
            for name, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
                out[name] = round(self.quantile(q), 9)
            out["min"] = round(self.min, 9)
            out["max"] = round(self.max, 9)
        return out


# -- ring buffer -------------------------------------------------------------


class RingLog:
    """Bounded event list: appending past ``retention`` evicts the
    OLDEST entry through ``on_evict`` (which folds it into running
    aggregates — :class:`Histogram` and counters — so a long-lived
    server's summary stays correct after eviction, at fixed memory).

    Quacks like the list it replaces in ``MetricsLogger``: iteration,
    ``len``, indexing, truthiness all behave identically for retained
    entries."""

    def __init__(self, retention: int = 4096, on_evict=None):
        if retention < 1:
            raise ValueError(f"retention must be >= 1: {retention}")
        self.retention = retention
        self.on_evict = on_evict
        self.evicted = 0
        self._items: list = []

    def append(self, item) -> None:
        self._items.append(item)
        if len(self._items) > self.retention:
            old = self._items.pop(0)
            self.evicted += 1
            if self.on_evict is not None:
                self.on_evict(old)

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator:
        return iter(list(self._items))

    def __getitem__(self, i):
        return self._items[i]

    def __bool__(self) -> bool:
        return bool(self._items)

    def clear(self) -> None:
        self._items.clear()


# -- SLO ---------------------------------------------------------------------


def slo_summary(
    target_p99_ms: float,
    latencies_ms,
    *,
    objective: float = 0.99,
    evicted_requests: int = 0,
    evicted_violations: int = 0,
    p99_ms: float | None = None,
) -> dict:
    """SLO attainment + error-budget burn for a declared p99 target.

    ``latencies_ms`` is the LIVE (ring-retained) rolling window;
    ``evicted_*`` carry the folded lifetime counts, so attainment is
    reported both for the rolling window and the whole run. Burn rate
    is the standard SRE definition: the fraction of requests violating
    the target divided by the budgeted fraction (``1 - objective``) —
    1.0 means burning budget exactly as fast as allowed, >1 means the
    SLO fails if sustained.

    Burn is reported over TWO windows side by side (``out["burn"]``,
    docs/OBSERVABILITY.md): ``fast`` over the rolling ring window
    (a flash crowd spikes it immediately, then it decays as healthy
    requests refill the ring) and ``slow`` over the whole run's
    lifetime counts (a slow regression creeps it up and a burst barely
    moves it) — the pairing that distinguishes transient incidents
    from sustained SLO erosion. ``budget_burn`` stays the lifetime
    (slow) number for backward compatibility; the rolling window's own
    burn also appears as ``window["budget_burn"]``.
    """
    window = [float(v) for v in latencies_ms]
    w_viol = sum(1 for v in window if v > target_p99_ms)
    requests = len(window) + evicted_requests
    violations = w_viol + evicted_violations
    budget = max(1.0 - objective, 1e-9)
    out: dict = {
        "target_p99_ms": target_p99_ms,
        "objective": objective,
        "requests": requests,
        "violations": violations,
    }
    if p99_ms is None and window:
        ws = sorted(window)
        p99_ms = ws[min(len(ws) - 1, int(len(ws) * objective))]
    if p99_ms is not None:
        out["p99_ms"] = round(p99_ms, 3)
        out["attained"] = bool(p99_ms <= target_p99_ms)
    if requests:
        attainment = 1.0 - violations / requests
        slow_burn = round((violations / requests) / budget, 4)
        out["attainment"] = round(attainment, 6)
        out["error_budget"] = round(budget, 6)
        out["budget_burn"] = slow_burn
        fast_burn = (
            round((w_viol / len(window)) / budget, 4) if window
            else slow_burn
        )
        out["burn"] = {"fast": fast_burn, "slow": slow_burn}
    if window:
        out["window"] = {
            "requests": len(window),
            "violations": w_viol,
            "attainment": round(1.0 - w_viol / len(window), 6),
            "budget_burn": round((w_viol / len(window)) / budget, 4),
        }
    return out
