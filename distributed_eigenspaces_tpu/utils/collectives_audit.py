"""Machine-checked collective-traffic audit (round-5 verdict item 2).

The framework's multi-chip story rests on one structural claim: the
merge moves the ``(m, d, k)`` factor stack (an ``all_gather``) instead
of a ``d x d`` mean projector (a ``psum``) — 2·d/(m·k)× less ICI traffic
at the benchmark shapes (16× at d=1024, m=8, k=8) — and the
feature-sharded solvers reduce only k-wide payloads. Until round 5 that
claim was prose + construction (`ops/linalg.py` docstring); the
reference's wire cost was at least *observable* on its broker
(``distributed.py:51``). This module makes ours machine-checked: parse
the collectives out of the COMPILED (SPMD-partitioned) HLO, compare
them against the documented model, and fail a test if a future change
silently reintroduces a dense allreduce.

Works on the CPU virtual-device mesh (the partitioner emits the same
collective ops it would for ICI), so the audit runs in plain pytest and
inside ``dryrun_multichip``.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass

# one optimized-HLO collective per line. Two result forms:
#   %ag = f32[8,128,4]{...} all-gather(%p), replica_groups=...
#   %rs = (f32[64]{0}, u32[]) all-reduce-start(%p), ...   (async / tuple)
# The op-name alternation accepts the async "-start" suffix (TPU HLO
# lowers collectives to start/done pairs) and "-done" is deliberately
# NOT matched (it would double-count its start's payload).
_OP_NAMES = (
    "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    "all-to-all",
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
# The tuple branch matches LAZILY up to the closing ") <op-name>(": TPU
# tiled layouts put parens INSIDE the tuple members (e.g.
# "(f32[64]{0:T(256)}, u32[])"), so a greedy-to-first-')' matcher would
# truncate mid-member and the parser-drift tripwire would raise on every
# TPU-compiled module (ADVICE.md r5).
_COLLECTIVE_RE = re.compile(
    r" = (\(.*?\)|\w+\[[\d,]*\][^ ]*) "
    r"(" + "|".join(_OP_NAMES) + r")(?:-start)?"
    r"\("
)
# raw occurrence counter for the parser-drift tripwire (see
# parse_collectives): "-done" ops and the start forms both contain the
# base name, so count call sites `name(` and `name-start(` only
_RAW_RE = re.compile(
    r"(" + "|".join(_OP_NAMES) + r")(?:-start)?\("
)

_ITEMSIZE = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "s8": 1, "u8": 1, "pred": 1,
}


@dataclass(frozen=True)
class CollectiveOp:
    op: str  # all-gather / all-reduce / ...
    dtype: str
    shape: tuple[int, ...]

    @property
    def elems(self) -> int:
        return math.prod(self.shape) if self.shape else 1

    @property
    def payload_bytes(self) -> int:
        return self.elems * _ITEMSIZE.get(self.dtype, 4)


def parse_collectives(hlo_text: str) -> list[CollectiveOp]:
    """Every collective op in an (optimized, SPMD-partitioned) HLO dump.

    Shapes are PER-DEVICE — an ``all-gather`` line's shape is its
    gathered output on each device. Tuple-shaped results (async
    ``-start`` forms, combined collectives) contribute the LARGEST
    member as the op's shape — the quantity the dense tripwire checks —
    and a tripwire guards the parser itself: if the text contains more
    collective call sites than the structured regex matched, the parser
    has drifted from the HLO syntax and raises instead of silently
    under-reporting (an empty parse must never read as "no dense
    collectives"). Ops inside a ``while`` body (the ``lax.scan`` steps)
    appear once in the text; callers reason per step, which is exactly
    the granularity the byte model wants.
    """
    out = []
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        shapes_txt, op = m.groups()
        members = [
            (dt, tuple(int(s) for s in dims.split(",") if s))
            for dt, dims in _SHAPE_RE.findall(shapes_txt)
        ]
        if not members:
            members = [("f32", ())]  # shapeless scalar result
        dtype, dims = max(
            members, key=lambda p: math.prod(p[1]) if p[1] else 1
        )
        out.append(CollectiveOp(op=op, dtype=dtype, shape=dims))
    raw = len(_RAW_RE.findall(hlo_text))
    if raw > len(out):
        raise RuntimeError(
            f"collective parser drift: {raw} collective call sites in "
            f"the HLO but only {len(out)} parsed — the audit would "
            "under-report; fix _COLLECTIVE_RE for the new syntax"
        )
    return out


def audit_compiled(compiled) -> dict:
    """Summary of a ``jit(...).lower(...).compile()`` result's collectives:
    per-(op, dtype, shape) counts plus the largest single payload —
    the number the dense-allreduce tripwire checks."""
    ops = parse_collectives(compiled.as_text())
    counts: dict[str, int] = {}
    for o in ops:
        key = f"{o.op} {o.dtype}[{','.join(map(str, o.shape))}]"
        counts[key] = counts.get(key, 0) + 1
    return {
        "ops": counts,
        "n_collectives": len(ops),
        "max_payload_elems": max((o.elems for o in ops), default=0),
        "max_payload_bytes": max(
            (o.payload_bytes for o in ops), default=0
        ),
        "_parsed": ops,
    }


def assert_no_dense_collective(audit: dict, dim: int) -> None:
    """The regression tripwire: no collective payload may reach ``d^2``
    elements (or even half of it) — the structural invariant every
    sharded trainer maintains is that ONLY factor stacks (m·d·k) and
    k-wide reductions cross the mesh, never a dense d x d matrix. A
    reintroduced dense-projector psum trips this immediately."""
    limit = dim * dim // 2
    worst = audit["max_payload_elems"]
    if worst >= limit:
        offenders = [
            f"{o.op} {o.dtype}{list(o.shape)}"
            for o in audit["_parsed"]
            if o.elems >= limit
        ]
        raise AssertionError(
            f"dense collective detected: payload {worst} elems >= "
            f"d^2/2 = {limit} ({', '.join(offenders)}) — the merge must "
            "move factors, not d x d matrices (ops/linalg.py "
            "merged_top_k_lowrank; BASELINE.md item 4)"
        )


def ici_step_model(
    m: int, d: int, k: int, *,
    n_workers_mesh: int, n_feature_shards: int = 1, itemsize: int = 4,
) -> dict:
    """Documented per-step ICI byte model for the sharded trainers,
    ring-collective accounting (what XLA lowers to on a torus):

    - factor merge: ``all_gather`` of per-device ``(m/W, d_l, k)`` shards
      into ``(m, d_l, k)`` on each of W worker-mesh devices — each
      device moves ``(W-1)/W * m * d_l * k`` elements per step
      (``d_l = d / n_feature_shards``);
    - the dense alternative this design replaces: ``psum`` of a
      ``d x d`` projector — ``2 * (W-1)/W * d^2`` elements per device;
    - feature-axis reductions (sharded matvec / CholeskyQR Grams /
      sketch folds): k-wide payloads, O(n·k + k^2) elements — reported
      as a bound, not enumerated (each is <= the merge payload by
      construction; the audit asserts the ceiling).

    Returns modeled bytes/device/step for the factor route, the dense
    route, and their ratio — the number BASELINE.md's "16x less ICI
    traffic" claim quotes, now computed instead of asserted in prose.
    """
    w = max(n_workers_mesh, 1)
    d_local = d // max(n_feature_shards, 1)
    ring = (w - 1) / w if w > 1 else 0.0
    factor = ring * m * d_local * k * itemsize
    dense = 2.0 * ring * d * d * itemsize
    return {
        "factor_gather_bytes_per_step": int(factor),
        "dense_psum_bytes_per_step": int(dense),
        # None (not inf) when the worker axis is trivial — a 1-chip mesh
        # moves nothing, and inf is not valid strict JSON
        "dense_over_factor": (
            round(dense / factor, 2) if factor else None
        ),
        "model": "ring collectives: all_gather (W-1)/W*payload, "
                 "psum 2*(W-1)/W*payload, per device per step",
    }


def scaling_projection(
    m: int, d: int, k: int, *, step_seconds: float,
    n_workers_mesh: int, n_feature_shards: int = 1,
    ici_gbps: float = 90.0,
) -> dict:
    """ICI-bytes-per-step vs step-time projection: at what mesh size
    does the merge's collective stop hiding behind the step's compute?
    ``ici_gbps`` defaults to a single v5e ICI link's ~90 GB/s (4800
    Gbps bidirectional across 4 links per chip / conservative per-link
    share); the point of the field is the RATIO trend, not the last
    percent — both inputs are in the JSON so readers can re-anchor.
    """
    model = ici_step_model(
        m, d, k,
        n_workers_mesh=n_workers_mesh,
        n_feature_shards=n_feature_shards,
    )
    wire_s = model["factor_gather_bytes_per_step"] / (ici_gbps * 1e9)
    return {
        **model,
        "assumed_ici_gb_per_sec": ici_gbps,
        "modeled_collective_seconds_per_step": round(wire_s, 9),
        "measured_step_seconds": round(step_seconds, 9),
        "collective_fraction_of_step": (
            round(wire_s / step_seconds, 6) if step_seconds > 0 else None
        ),
    }
