"""Back-compat shim: the collective-traffic audit moved to
``distributed_eigenspaces_tpu.analysis.hlo`` (PR 10), where it is one
pass of the program-contract analyzer (``analysis/contracts.py``,
driven by ``scripts/analyze.py``).

This module re-exports the old public names and warns ONCE per
process; new code should import from ``analysis.hlo`` (parser) or use
the contract API (``analysis.contracts.check_program``) directly.
"""

from __future__ import annotations

import warnings

from distributed_eigenspaces_tpu.analysis.hlo import (  # noqa: F401
    AuditParseError,
    CollectiveOp,
    assert_no_dense_collective,
    audit_compiled,
    ici_step_model,
    parse_collectives,
    scaling_projection,
)

warnings.warn(
    "distributed_eigenspaces_tpu.utils.collectives_audit is a "
    "back-compat shim: import from "
    "distributed_eigenspaces_tpu.analysis.hlo (parser) or use the "
    "contract API in distributed_eigenspaces_tpu.analysis.contracts",
    DeprecationWarning,
    stacklevel=2,
)
