"""Compile-lifecycle subsystem: persistent cache + AOT executable store.

The warm path of this system is fast (BENCH_r05: 0.307 ms/step) and the
cold path is dominated by XLA compilation (~7.4 s first step on the CPU
rig, BASELINE.md) — a cost every new process pays again, and one the
serving tiers (``parallel/fleet.FleetServer``,
``serving/server.QueryServer``) pay INLINE on the first request of each
shape signature. TPU linear-algebra practice treats a compiled program
as a one-time artifact to be cached and reused across processes
(arXiv:2112.09017); this module is that artifact store, two layers deep:

1. **XLA's persistent compilation cache**
   (:func:`configure_persistent_cache`): ``jax_compilation_cache_dir``
   pointed at ``<dir>/xla``. Transparent — every jit in the process
   benefits — but it only skips the XLA backend compile; tracing and
   lowering still run, and the cache key is XLA's, not ours.

2. **An explicit AOT layer** (:class:`CompileCache`): compiled
   executables serialized via ``jax.experimental.serialize_executable``
   and keyed by ``(program kind, shape signature, dtype, backend, jax
   version, relevant PCAConfig knobs)``. A warm process deserializes
   the executable directly — no tracing, no lowering, no XLA — which is
   where the order-of-magnitude cold-start win lives (measured in
   ``bench.py --coldstart``). Results are bit-identical cached-vs-fresh
   (pinned in tests): deserialization reloads the SAME executable bytes
   a fresh compile would produce on this backend.

Fallback ladder (every rung loud, no rung fatal): in-memory hit →
disk hit (meta validated: key string, jax version, backend, format) →
fresh compile (+ best-effort persist). A corrupt, truncated, or
version-mismatched disk entry warns and falls through to the fresh
compile — a cache must never change results or crash a run.

**CPU portability guard.** On the CPU backend, an executable containing
``custom_call`` sites (LAPACK eigh/Cholesky — every solver program
here) embeds raw host function pointers: deserializing it in ANOTHER
process calls into the old process's address space — measured as a
segfault on this rig's jaxlib. The disk tier therefore persists a CPU
executable only when its lowered module is custom-call-free (the
transform kernels — pure matmuls — qualify; the fit programs do not).
Non-portable programs still get the in-memory AOT tier, the Prewarmer,
and layer 1's XLA persistent cache — which stores pre-link artifacts
and relocates correctly, and is where the CPU rig's measured cold-start
win on fit programs comes from (``bench.py --coldstart``). TPU/GPU
executables serialize by design and skip the guard.

Keys deliberately EXCLUDE ``PCAConfig.seed``: the AOT-cached programs
(dense scan fit, fleet fit/extract, transform kernels) take all
randomness-free inputs as operands — the subspace solver's cold init
inside them is the fixed ``PRNGKey(0)`` basis, not a seed-derived
constant. Programs that bake ``seed`` in (the feature-sharded
trainers) are not AOT-cached here; they ride layer 1 only.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import threading
import time
import warnings

__all__ = [
    "CacheKey",
    "CompileCache",
    "compile_cache_for",
    "config_knobs",
    "configure_persistent_cache",
    "make_key",
]

#: bump when the on-disk entry layout changes: old entries then fail
#: meta validation and fall back to a fresh compile instead of
#: deserializing garbage
_FORMAT_VERSION = 1

#: PCAConfig fields that change the COMPILED PROGRAM for the AOT-cached
#: kinds (shape fields ride in the key's signature; ``backend``/device
#: ride in the key's backend; ``seed`` is deliberately absent — see the
#: module docstring). Warm-start and warm-orth are keyed at their
#: RESOLVED values so "auto" and its resolution can never alias two
#: different programs under one key.
_PROGRAM_KNOB_FIELDS = (
    "discount",
    "solver",
    "subspace_iters",
    "orth_method",
    "compute_dtype",
    "stage_dtype",
    "dtype",
    "state_dtype",
    "collectives",
    "merge_interval",
    "pipeline_merge",
)


def config_knobs(cfg) -> tuple[tuple[str, str], ...]:
    """The program-affecting PCAConfig knobs as a canonical
    ``((name, repr), ...)`` tuple — the ``knobs`` half of every
    config-derived :class:`CacheKey` (one definition, so two call sites
    cannot disagree about which knobs invalidate the cache)."""
    knobs = [(f, repr(getattr(cfg, f))) for f in _PROGRAM_KNOB_FIELDS]
    knobs.append(("warm_start", repr(cfg.resolved_warm_start())))
    knobs.append(("warm_orth", repr(cfg.resolved_warm_orth())))
    return tuple(knobs)


@dataclasses.dataclass(frozen=True)
class CacheKey:
    """One AOT cache key: everything that must match for a serialized
    executable to be valid to reuse. Two keys with ANY differing field
    map to different digests — changing ``k``, a dtype, a solver knob,
    the jax version, or the backend is a MISS by construction (pinned
    in tests/test_compile_cache.py)."""

    kind: str  # program kind: "scan_fit", "fleet_fit", "transform_project", ...
    signature: tuple  # shape signature (kind-specific, hashable)
    dtype: str  # primary operand dtype
    backend: str  # jax.default_backend() at key time
    jax_version: str  # jax.__version__ at key time
    knobs: tuple = ()  # ((name, repr), ...) program-affecting config knobs

    def string(self) -> str:
        return (
            f"fmt{_FORMAT_VERSION}|kind={self.kind}"
            f"|sig={self.signature!r}|dtype={self.dtype}"
            f"|backend={self.backend}|jax={self.jax_version}"
            f"|knobs={self.knobs!r}"
        )

    def digest(self) -> str:
        return hashlib.sha256(self.string().encode()).hexdigest()[:32]


def make_key(
    kind: str,
    signature: tuple,
    dtype,
    *,
    knobs: tuple = (),
    backend: str | None = None,
    jax_version: str | None = None,
) -> CacheKey:
    """Build a :class:`CacheKey` with the runtime defaults resolved
    (current backend, current jax version). Tests override both to
    prove version/backend invalidation without actually swapping
    runtimes."""
    import jax

    return CacheKey(
        kind=kind,
        signature=tuple(signature),
        dtype=str(dtype),
        backend=jax.default_backend() if backend is None else backend,
        jax_version=jax.__version__ if jax_version is None else jax_version,
        knobs=tuple(knobs),
    )


class CompileCache:
    """Two-tier AOT executable cache: per-process memory + optional disk.

    ``get_or_build(key, lower_fn)`` returns a compiled executable for
    ``key``; ``lower_fn()`` must return a ``jax.stages.Lowered`` (i.e.
    ``jax.jit(f).lower(*shape_structs)``) and is only invoked on a full
    miss. Counters (:meth:`stats`) make the lifecycle auditable:
    ``hits`` (memory), ``disk_hits`` (deserialized — the cross-process
    warm start), ``misses`` (fresh compiles), ``fallbacks`` (disk
    entries rejected loudly), and ``compile_ms_total`` (wall time spent
    ACQUIRING programs — fresh compiles dominate it, disk hits barely
    register, which is exactly the claim ``bench.py --coldstart``
    measures).

    ``cache_dir=None`` is a memory-only cache: same AOT discipline and
    honest compile timing, no persistence — what the serving tiers use
    when no ``compile_cache_dir`` is configured.
    """

    def __init__(self, cache_dir: str | None = None):
        self.cache_dir = None if cache_dir is None else str(cache_dir)
        if self.cache_dir is not None:
            os.makedirs(self.cache_dir, exist_ok=True)
        self._mem: dict[str, object] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.disk_hits = 0
        self.misses = 0
        self.fallbacks = 0
        self.not_portable = 0
        self.compile_ms_total = 0.0
        self.last_compile_ms = 0.0
        #: optional ``utils.telemetry.Tracer`` (set by
        #: ``MetricsLogger.attach_compile``/``attach_tracer``): cache
        #: hits land as instant events, fresh compiles as spans, so
        #: compile stalls are attributable on the exported timeline
        self.tracer = None

    # -- paths ---------------------------------------------------------------

    def _paths(self, key: CacheKey) -> tuple[str, str]:
        d = key.digest()
        return (
            os.path.join(self.cache_dir, f"{d}.json"),
            os.path.join(self.cache_dir, f"{d}.bin"),
        )

    # -- disk tier -----------------------------------------------------------

    def _load_disk(self, key: CacheKey):
        """Deserialize a disk entry for ``key``, or None. EVERY failure
        mode — missing files, corrupt/truncated pickle, meta whose key
        string, jax version, backend, or format does not match the
        current runtime — warns and returns None (the fresh-compile
        fallback), never raises."""
        if self.cache_dir is None:
            return None
        meta_path, bin_path = self._paths(key)
        if not (os.path.exists(meta_path) and os.path.exists(bin_path)):
            return None
        import jax
        from jax.experimental import serialize_executable

        try:
            with open(meta_path) as f:
                meta = json.load(f)
            bad = None
            if meta.get("format") != _FORMAT_VERSION:
                bad = f"format {meta.get('format')} != {_FORMAT_VERSION}"
            elif meta.get("key") != key.string():
                bad = "key string mismatch (digest collision or tamper)"
            elif meta.get("jax_version") != jax.__version__:
                bad = (
                    f"jax {meta.get('jax_version')} != {jax.__version__}"
                )
            elif meta.get("backend") != jax.default_backend():
                bad = (
                    f"backend {meta.get('backend')} != "
                    f"{jax.default_backend()}"
                )
            if bad is not None:
                raise ValueError(bad)
            with open(bin_path, "rb") as f:
                payload, in_tree, out_tree = pickle.loads(f.read())
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree
            )
        except Exception as e:  # corrupt/truncated/mismatched: fall back
            with self._lock:
                self.fallbacks += 1
            warnings.warn(
                f"compile cache entry for {key.kind} {key.signature} is "
                f"invalid ({e!r}) — falling back to a fresh compile "
                "(results are unaffected; the entry will be rewritten)",
                stacklevel=3,
            )
            return None

    def _store_disk(self, key: CacheKey, compiled) -> None:
        """Best-effort persist: a program that cannot serialize (or a
        read-only cache dir) costs the NEXT process a compile, never
        this one a crash."""
        if self.cache_dir is None:
            return
        import jax
        from jax.experimental import serialize_executable

        try:
            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            meta_path, bin_path = self._paths(key)
            tmp = bin_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(pickle.dumps((payload, in_tree, out_tree)))
            os.replace(tmp, bin_path)  # atomic: readers never see a torn blob
            with open(meta_path + ".tmp", "w") as f:
                json.dump(
                    {
                        "format": _FORMAT_VERSION,
                        "key": key.string(),
                        "kind": key.kind,
                        "jax_version": jax.__version__,
                        "backend": jax.default_backend(),
                        "written_at": time.time(),
                    },
                    f,
                )
            os.replace(meta_path + ".tmp", meta_path)
        except Exception as e:
            from distributed_eigenspaces_tpu.utils.metrics import log_line

            log_line(
                "compile cache persist failed (executable not "
                "serializable or cache dir unwritable) — continuing "
                "with the in-memory compile",
                kind=key.kind,
                error=repr(e),
            )

    # -- the one entry point -------------------------------------------------

    def get_or_build(self, key: CacheKey, lower_fn):
        """The compiled executable for ``key``: memory hit → disk hit
        (deserialize) → fresh ``lower_fn().compile()`` (persisted
        best-effort). ``lower_fn`` returns a ``jax.stages.Lowered``."""
        from distributed_eigenspaces_tpu.utils.telemetry import tracer_of

        tr = tracer_of(self)
        s = key.string()
        with self._lock:
            hit = self._mem.get(s)
            if hit is not None:
                self.hits += 1
                tr.event(
                    "compile_cache_hit", category="compile",
                    attrs={"kind": key.kind, "tier": "memory"},
                )
                return hit
        loaded = self._load_disk(key)
        if loaded is not None:
            with self._lock:
                self.disk_hits += 1
                self._mem[s] = loaded
            tr.event(
                "compile_cache_hit", category="compile",
                attrs={"kind": key.kind, "tier": "disk"},
            )
            return loaded
        t0 = time.perf_counter()
        lowered = lower_fn()
        compiled = lowered.compile()
        dt_ms = (time.perf_counter() - t0) * 1e3
        tr.record_span(
            "compile", t0, time.perf_counter(), category="compile",
            attrs={
                "kind": key.kind, "signature": repr(key.signature),
            },
        )
        if self._portable(key, lowered):
            self._store_disk(key, compiled)
        with self._lock:
            self.misses += 1
            self.compile_ms_total += dt_ms
            self.last_compile_ms = dt_ms
            self._mem[s] = compiled
        return compiled

    def _portable(self, key: CacheKey, lowered) -> bool:
        """Whether ``lowered``'s executable may be deserialized by a
        DIFFERENT process (the module docstring's CPU portability
        guard). Conservative on inspection failure: not portable."""
        if self.cache_dir is None:
            return False  # memory-only cache: nothing to persist
        if key.backend != "cpu":
            return True
        try:
            portable = "custom_call" not in lowered.as_text()
        except Exception:
            portable = False
        if not portable:
            with self._lock:
                self.not_portable += 1
        return portable

    def contains(self, key: CacheKey) -> bool:
        """Whether ``key`` would be served without an XLA compile
        (memory or a validatable disk entry) — the prewarm assertion's
        question. Does not bump counters and does not deserialize."""
        with self._lock:
            if key.string() in self._mem:
                return True
        if self.cache_dir is None:
            return False
        meta_path, bin_path = self._paths(key)
        return os.path.exists(meta_path) and os.path.exists(bin_path)

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "disk_hits": self.disk_hits,
                "misses": self.misses,
                "fallbacks": self.fallbacks,
                "not_portable": self.not_portable,
                "compile_ms_total": round(self.compile_ms_total, 3),
                "entries_mem": len(self._mem),
                "dir": self.cache_dir,
            }


# -- wiring ------------------------------------------------------------------

_CONFIGURED_DIRS: set[str] = set()
_INSTANCES: dict[str, CompileCache] = {}
_WIRING_LOCK = threading.Lock()


def configure_persistent_cache(cache_dir: str) -> str:
    """Point JAX's persistent compilation cache at ``<cache_dir>/xla``
    (layer 1 of the module docstring). Thresholds are zeroed so even
    the CPU rig's fast-compiling smoke programs land on disk — on a
    real TPU every entry clears the default thresholds anyway.
    Idempotent; returns the XLA cache dir."""
    import jax

    xla_dir = os.path.join(str(cache_dir), "xla")
    with _WIRING_LOCK:
        if xla_dir in _CONFIGURED_DIRS:
            return xla_dir
        os.makedirs(xla_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # the cache object initializes LAZILY at the first compile
            # and never re-reads the dir config: a process that compiled
            # anything before this call would silently run with the
            # cache pointed elsewhere (or nowhere) — measured as the
            # entire cross-process warm-start win disappearing. Reset
            # so the next compile re-initializes against xla_dir.
            from jax.experimental.compilation_cache import (
                compilation_cache as _xla_cc,
            )

            _xla_cc.reset_cache()
        except Exception:
            pass  # older/newer jax: the config alone has to do
        _CONFIGURED_DIRS.add(xla_dir)
    return xla_dir


def compile_cache_for(cfg) -> CompileCache | None:
    """The process-wide :class:`CompileCache` for ``cfg``'s
    ``compile_cache_dir`` (AOT blobs under ``<dir>/aot``, XLA cache
    wired under ``<dir>/xla``), or None when the knob is unset. One
    instance per directory, so the estimator, the fleet server, and the
    query server of one process share counters and the memory tier."""
    cache_dir = getattr(cfg, "compile_cache_dir", None)
    if cache_dir is None:
        return None
    configure_persistent_cache(cache_dir)
    aot_dir = os.path.abspath(os.path.join(str(cache_dir), "aot"))
    with _WIRING_LOCK:
        inst = _INSTANCES.get(aot_dir)
        if inst is None:
            inst = CompileCache(aot_dir)
            _INSTANCES[aot_dir] = inst
        return inst
