"""Tracing / profiling (SURVEY.md §5.1).

The reference's tracing is print statements at protocol steps plus tqdm in
the notebook. TPU-native: ``jax.named_scope`` annotations (show up in XLA/
profiler timelines around shard compute and the merge) and ``jax.profiler``
trace capture for TensorBoard.

Since ISSUE 6 this module is also the DEVICE half of the unified
telemetry layer: host-side spans (``utils/telemetry.Tracer``) opened
with ``device=True`` enter :func:`trace_annotation`, so when a
``jax.profiler`` capture (:func:`profile_to`) runs alongside, the same
request-scoped names annotate the device timeline — one vocabulary
across the exported Chrome trace and the XLA profile.
"""

from __future__ import annotations

import contextlib

import jax


def named_scope(name: str):
    """Annotate a region of traced computation (visible in profiles)."""
    return jax.named_scope(name)


def trace_annotation(name: str):
    """Annotate a region of HOST execution so it shows on the jax
    profiler timeline (device-correlated). This is what merges
    ``telemetry.Tracer`` spans into a ``profile_to`` capture: the span
    name brackets the dispatch on the profiler's host track, next to
    the ``named_scope`` regions it launched."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile_to(log_dir: str | None):
    """Capture a jax.profiler trace into ``log_dir`` (no-op when None)::

        with profile_to("/tmp/trace"):
            state, _ = step(state, x)
            jax.block_until_ready(state)
    """
    if log_dir is None:
        yield
        return
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate_step(t: int):
    """Name one online step in the profile timeline."""
    with jax.profiler.StepTraceAnnotation("pca_step", step_num=t):
        yield
