"""Whole-fit training: the entire T-step online loop as ONE XLA program.

``make_train_step`` (algo/step.py) already fuses one round end-to-end; this
module goes one level further and puts the outer ``t = 1..T`` loop (notebook
cell 16's Python ``for``) inside the compiled program as a ``lax.scan`` —
zero host involvement between steps, no per-step dispatch latency (which
dominates when the host drives the device over a network tunnel), and XLA
can overlap the collective of step t with compute of step t+1.

The data for all T steps must be device-resident ``(T, m, n, d)`` — right
for benchmark loops and moderate T; for unbounded streams use the
per-step path with ``runtime.prefetch``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_eigenspaces_tpu.algo.online import OnlineState, update_state
from distributed_eigenspaces_tpu.algo.step import (
    make_round_core,
    make_warm_core,
)
from distributed_eigenspaces_tpu.config import PCAConfig
from distributed_eigenspaces_tpu.parallel.mesh import WORKER_AXIS, shard_map


def _masked_body_factory(cfg, round_core, warm_core, axis_name, update):
    """ONE uniform masked step body shared by the masked scan and
    segmented programs: per-step cold-vs-warm dispatch on the carry
    itself (``lax.cond`` on "has any live round happened"), so a
    killed-and-resumed masked run is bit-for-bit the unkilled one and an
    all-masked FIRST round recovers instead of freezing a zero basis
    (zeros are a fixed point of the warm solver). Semantics are the
    per-step masked loop's exactly (tested equivalence): every round
    folds its merge result — zeros on an all-masked round — and the warm
    carry keeps the last LIVE basis.
    """
    warm = warm_core is not None

    def body(carry, x, mk):
        st, vp = carry
        if warm:
            live = jnp.any(vp != 0)
            v_bar = jax.lax.cond(
                live,
                lambda xx, mm, vv: warm_core(
                    xx, axis_name=axis_name, v0=vv, mask=mm
                ),
                lambda xx, mm, vv: round_core(
                    xx, axis_name=axis_name, mask=mm
                ),
                x, mk, vp,
            )
        else:
            v_bar = round_core(x, axis_name=axis_name, mask=mk)
        # liveness from the MASK row, not the merged result: the per-step
        # loop reads the mask on the host (algo/online.py), and a LIVE
        # round whose data happens to be all-zero merges to an exactly
        # zero v_bar — deriving liveness from v_bar would diverge from
        # the per-step semantics in that degenerate case (ADVICE.md r5)
        vp_next = jnp.where(jnp.any(mk != 0), v_bar, vp)
        return (update(st, v_bar), vp_next), v_bar

    return body


def make_scan_fit(
    cfg: PCAConfig, mesh: Mesh | None = None, *, gather: bool = False,
    masked: bool = False,
):
    """Build the whole-fit trainer, jitted.

    ``gather=False``: ``fit(state, x_steps) -> (state, v_bars)`` where
    ``x_steps`` is ``(T, m, n, d)`` — T online steps of m-worker blocks;
    ``v_bars`` is ``(T, d, k)``, the merged eigenspace after every step.

    ``gather=True``: ``fit(state, blocks, idx) -> (state, v_bars)`` where
    ``blocks`` is ``(B, m, n, d)`` distinct staged blocks and ``idx`` a
    ``(T,)`` int32 schedule — each scan step gathers ``blocks[idx[t]]``
    inside the body, so device memory stays O(B) instead of O(T) (the
    cycled-blocks benchmark pattern without materializing the cycle).

    Semantically identical to calling the per-step trainer T times (tested —
    both build on :func:`~..algo.step.make_round_core`), just compiled as
    one program.

    With ``cfg.warm_start_iters`` set (subspace solver only), the first
    step runs the full-iteration cold core and every later step warm-starts
    its per-worker solves from the previous merged ``v_bar`` with the short
    iteration count — the online-stream optimization BASELINE.md measures.

    ``masked=True`` builds the §5.3 fault-exclusion variant instead:
    ``fit(state, x_steps, masks) -> (state, v_bars)`` with ``masks`` a
    ``(T, m)`` {0,1} array — one program, per-step cold/warm dispatch on
    the carry (:func:`_masked_body_factory`), equivalent to the per-step
    masked loop (tested). The unmasked build stays the exact pre-mask
    program, so the throughput path pays nothing for the fault
    machinery. ``gather`` staging is not offered masked (masked fits are
    dense-staged by the estimator).
    """
    # function-level import: utils.__init__ pulls checkpoint, which
    # imports this module — a top-level import would cycle
    from distributed_eigenspaces_tpu.utils.guards import checked_jit

    if masked and gather:
        raise ValueError("masked scan fits take a dense (T, ...) stack")

    round_core = make_round_core(cfg)
    warm_core = make_warm_core(cfg)
    warm = warm_core is not None

    def make_fit(axis_name):
        def update(st, v_bar):
            return update_state(
                st, v_bar, discount=cfg.discount, num_steps=cfg.num_steps
            )

        if masked:
            mbody = _masked_body_factory(
                cfg, round_core, warm_core, axis_name, update
            )

            def fit_masked(state, x_steps, masks):
                k = cfg.k
                vp0 = jnp.zeros((cfg.dim, k), jnp.float32)
                (state, _), v_bars = jax.lax.scan(
                    lambda c, xm: mbody(c, xm[0], xm[1]),
                    (state, vp0),
                    (x_steps, masks.astype(jnp.float32)),
                )
                return state, v_bars

            return fit_masked

        def step_body(st, x):
            v_bar = round_core(x, axis_name=axis_name)
            return update(st, v_bar), v_bar

        def warm_body(carry, x):
            st, v_prev = carry
            v_bar = warm_core(x, axis_name=axis_name, v0=v_prev)
            return (update(st, v_bar), v_bar), v_bar

        def warm_fit(first_x, scan_body, xs_rest, state):
            # step 1: cold, full iterations (also the resume-safe path:
            # no solver state is assumed to exist)
            v0_bar = round_core(first_x, axis_name=axis_name)
            state = update(state, v0_bar)
            (state, _), v_bars = jax.lax.scan(
                scan_body, (state, v0_bar), xs_rest
            )
            return state, jnp.concatenate([v0_bar[None], v_bars], axis=0)

        if warm and gather:

            def fit(state, blocks, idx):
                def body(carry, i):
                    return warm_body(carry, blocks[i])

                return warm_fit(blocks[idx[0]], body, idx[1:], state)

            return fit

        if warm:

            def fit(state, x_steps):
                return warm_fit(
                    x_steps[0], warm_body, x_steps[1:], state
                )

            return fit

        if gather:

            def fit_gather(state, blocks, idx):
                def body(st, i):
                    return step_body(st, blocks[i])

                return jax.lax.scan(body, state, idx)

            return fit_gather

        def fit_dense(state, x_steps):
            return jax.lax.scan(step_body, state, x_steps)

        return fit_dense

    if mesh is None:
        # checked_jit == jax.jit unless DET_CHECKIFY=1 (NaN guards, §5.2)
        return checked_jit(make_fit(axis_name=None))

    # one shard_map around the whole scan: the worker axis stays
    # device-resident across all T steps and only the k-width merge
    # crosses ICI each step
    rep = NamedSharding(mesh, P())
    x_sharding = NamedSharding(mesh, P(None, WORKER_AXIS))
    extra = (P(),) if (gather or masked) else ()  # idx / (T, m) masks
    in_specs = (P(), P(None, WORKER_AXIS)) + extra
    in_shardings = (rep, x_sharding) + ((rep,) if (gather or masked) else ())
    inner = shard_map(
        make_fit(axis_name=WORKER_AXIS),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P()),
        check_vma=False,
    )
    return checked_jit(
        inner, in_shardings=in_shardings, out_shardings=(rep, rep)
    )


class SegmentState(NamedTuple):
    """Checkpointable carry of the segmented scan trainer: the online state
    PLUS the warm-start carry (the last merged estimate), so a resumed run
    continues bit-for-bit — without ``v_prev`` the first post-resume step
    would have to run cold and diverge from the unkilled run.
    """

    sigma_tilde: jax.Array
    step: jax.Array  # int32 scalar, 1-based rounds folded in
    v_prev: jax.Array  # (d, k) last merged estimate; zeros before step 1

    @classmethod
    def initial(cls, dim: int, k: int, dtype=jnp.float32) -> "SegmentState":
        return cls(
            sigma_tilde=jnp.zeros((dim, dim), dtype=dtype),
            step=jnp.zeros((), jnp.int32),
            v_prev=jnp.zeros((dim, k), dtype=jnp.float32),
        )


def make_segmented_fit(cfg: PCAConfig, mesh: Mesh | None = None, *,
                       segment: int = 50):
    """Checkpointable whole-fit trainer: T steps run as ceil(T/S)
    ``lax.scan`` programs of S steps each, with a host hook between
    segments — ``fit(state, x_steps, on_segment=None) -> SegmentState``.

    This closes the round-1 gap "the fastest trainer can't checkpoint":
    per-segment dispatch costs 1/S of the per-step trainer's (S=50 keeps
    it ~2% on the tunneled dev host), while ``on_segment(steps_done,
    state)`` runs on the host between programs for checkpoint/metrics
    (utils/checkpoint.py saves ``SegmentState`` like any other state).

    Semantics are identical to :func:`make_scan_fit` on the same workload
    (same ``make_round_core``; with ``cfg.warm_start_iters`` the cold
    first step runs only when ``state.step == 0``, and the warm carry
    crosses segment AND checkpoint boundaries via ``state.v_prev``) —
    a killed-and-resumed run is bit-for-bit the unkilled run.

    ``x_steps`` may be a host array: each segment's slice is transferred
    as its program runs (O(S) device memory, not O(T)).
    """
    if segment < 1:
        raise ValueError(f"segment must be >= 1, got {segment}")
    from distributed_eigenspaces_tpu.utils.guards import checked_jit

    round_core = make_round_core(cfg)
    warm_core = make_warm_core(cfg)
    warm = warm_core is not None

    def update(st, v_bar):
        return update_state(
            st, v_bar, discount=cfg.discount, num_steps=cfg.num_steps
        )

    def make_seg(axis_name, first):
        core = warm_core if warm else round_core

        def body(carry, x):
            st, vp = carry
            v = (
                core(x, axis_name=axis_name, v0=vp) if warm
                else core(x, axis_name=axis_name)
            )
            return (update(st, v), v), None

        def seg(sstate, x_steps):
            st = OnlineState(sstate.sigma_tilde, sstate.step)
            vp = sstate.v_prev
            if warm and first:
                # cold first step at the full iteration count
                vp = round_core(x_steps[0], axis_name=axis_name)
                st = update(st, vp)
                x_steps = x_steps[1:]
            (st, vp), _ = jax.lax.scan(body, (st, vp), x_steps)
            return SegmentState(st.sigma_tilde, st.step, vp)

        return seg

    def make_seg_masked(axis_name):
        """§5.3 masked window program — ONE program for every window,
        first or continuation: per-step cold/warm dispatch on the carry
        (:func:`_masked_body_factory`), so kill/resume is bit-for-bit
        and an all-masked first round recovers cold."""
        mbody = _masked_body_factory(
            cfg, round_core, warm_core, axis_name, update
        )

        def body(c, xm):
            carry, _ = mbody(c, xm[0], xm[1])
            return carry, None

        def seg(sstate, x_steps, masks):
            st = OnlineState(sstate.sigma_tilde, sstate.step)
            (st, vp), _ = jax.lax.scan(
                body,
                (st, sstate.v_prev),
                (x_steps, masks.astype(jnp.float32)),
            )
            return SegmentState(st.sigma_tilde, st.step, vp)

        return seg

    if mesh is None:
        def build(first):
            return checked_jit(make_seg(None, first))

        def build_masked():
            return checked_jit(make_seg_masked(None))
    else:
        rep = NamedSharding(mesh, P())
        x_sharding = NamedSharding(mesh, P(None, WORKER_AXIS))

        def build(first):
            inner = shard_map(
                make_seg(WORKER_AXIS, first),
                mesh=mesh,
                in_specs=(P(), P(None, WORKER_AXIS)),
                out_specs=P(),
                check_vma=False,
            )
            return checked_jit(
                inner, in_shardings=(rep, x_sharding), out_shardings=rep
            )

        def build_masked():
            inner = shard_map(
                make_seg_masked(WORKER_AXIS),
                mesh=mesh,
                in_specs=(P(), P(None, WORKER_AXIS), P()),
                out_specs=P(),
                check_vma=False,
            )
            return checked_jit(
                inner,
                in_shardings=(rep, x_sharding, rep),
                out_shardings=rep,
            )

    compiled = {}

    def _get(first, masked=False):
        key = (False, True) if masked else (first, False)
        if key not in compiled:
            compiled[key] = build_masked() if masked else build(first)
        return compiled[key]

    def fit_windows(
        state, windows, on_segment=None, worker_masks=None
    ) -> SegmentState:
        """Out-of-core variant: consume an ITERATOR of staged
        ``(S, m, n, d)`` windows instead of one resident ``(T, ...)``
        array — the whole-fit path for streams that never fit in device
        (or host) memory, e.g. the bin pipeline's 400M-row config.

        Each window runs as one S-step program; wrap the window source in
        :func:`~..runtime.prefetch.prefetch_stream` and window t+1's
        disk read + host convert + host->device transfer overlap window
        t's device program (the fit only fences at its caller's final
        value fetch). ``S`` may vary (a ragged tail window just
        specializes the jit once more); semantics are identical to
        :func:`fit` on the concatenation (same compiled programs —
        ``fit`` IS this function over a slice generator).

        ``worker_masks`` (an iterable of ``(S, m)`` {0,1} arrays
        parallel to ``windows``, zipped strict) runs the §5.3 masked
        window program instead — one cond-dispatch program for every
        window, so kill/resume stays bit-for-bit (the per-step
        cold/warm branch depends only on the restored carry).
        """
        # without warm start the "first" program is identical to the
        # continuation program — never compile it twice. A ZERO carry
        # must also run cold: zeros are a fixed point of the warm
        # solver (orth(0) = 0), so warm-starting from a restored state
        # that lacks v_prev (cross-trainer resume) would silently
        # discard every subsequent step. Evaluated once up front: after
        # the first window ``step > 0`` and ``v_prev`` is nonzero, so
        # re-fetching these scalars per window would pay two blocking
        # device->host round trips for a value that can only be False.
        first = warm and (
            int(state.step) == 0 or not bool(jnp.any(state.v_prev))
        )
        pairs = (
            ((w, None) for w in windows)
            if worker_masks is None
            else zip(windows, worker_masks, strict=True)
        )
        for w, mk in pairs:
            if mk is None:
                state = _get(first)(state, w)
            else:
                state = _get(first, masked=True)(
                    state, w, jnp.asarray(mk, jnp.float32)
                )
            first = False
            if on_segment is not None:
                on_segment(int(state.step), state)
        return state

    def fit(state: SegmentState, x_steps, on_segment=None) -> SegmentState:
        total = x_steps.shape[0]
        return fit_windows(
            state,
            (
                jnp.asarray(x_steps[t : t + segment])
                for t in range(0, total, segment)
            ),
            on_segment,
        )

    fit.segment = segment
    fit.fit_windows = fit_windows
    return fit
