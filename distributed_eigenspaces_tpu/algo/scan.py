"""Whole-fit training: the entire T-step online loop as ONE XLA program.

``make_train_step`` (algo/step.py) already fuses one round end-to-end; this
module goes one level further and puts the outer ``t = 1..T`` loop (notebook
cell 16's Python ``for``) inside the compiled program as a ``lax.scan`` —
zero host involvement between steps, no per-step dispatch latency (which
dominates when the host drives the device over a network tunnel), and XLA
can overlap the collective of step t with compute of step t+1.

The data for all T steps must be device-resident ``(T, m, n, d)`` — right
for benchmark loops and moderate T; for unbounded streams use the
per-step path with ``runtime.prefetch``.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_eigenspaces_tpu.algo.online import (
    OnlineState,
    update_state,
    update_state_projector,
)
from distributed_eigenspaces_tpu.algo.step import (
    make_round_core,
    make_solve_core,
    make_warm_core,
    make_warm_solve_core,
    mean_projector,
    merge_core,
)
from distributed_eigenspaces_tpu.config import PCAConfig
from distributed_eigenspaces_tpu.ops.linalg import projector
from distributed_eigenspaces_tpu.parallel.mesh import WORKER_AXIS, shard_map


def _merge_knobs(cfg: PCAConfig) -> dict:
    """Crossover-merge dispatch knobs for direct :func:`merge_core` call
    sites (the interval / pipelined scan bodies, which bypass
    ``make_round_core``): ``dist_iters`` routes the merge through the
    distributed subspace solver when ``cfg.uses_distributed_solve()``,
    ``deflate_lanes`` swaps it for the parallel-deflation lanes when
    ``cfg.uses_deflation_solve()`` (ISSUE 18), and ``dist_tol`` arms the
    gap-adaptive stop. All ``None`` below the crossover — the traced
    programs stay byte-identical to the pre-knob builds."""
    dist_iters = cfg.subspace_iters if cfg.uses_distributed_solve() else None
    deflate_lanes = (
        cfg.components_axis_size
        if (dist_iters is not None and cfg.uses_deflation_solve())
        else None
    )
    dist_tol = cfg.solver_tol if dist_iters is not None else None
    return {
        "dist_iters": dist_iters,
        "deflate_lanes": deflate_lanes,
        "dist_tol": dist_tol,
    }


def _merge_or_fold_factory(cfg: PCAConfig):
    """ONE definition of the merge-interval round fold, shared by every
    interval-aware body (unmasked/masked scan, pipelined scan,
    segmented): ``fold_round(st, vs, vp, mask=None) -> (st, v_new,
    merge_now)``. On merge rounds (``st.step % s == 0`` — steps 1, s+1,
    2s+1, ... in 1-based step numbers) the gathered factors run the
    exact low-rank merge and the merged projector ``v̄ v̄ᵀ`` is folded;
    between merges the masked MEAN of the worker projectors is folded
    at the same discount weight and ``v_new`` is the carried basis.
    ``lax.cond`` executes ONE branch, so fold rounds never pay the
    k-wide merge-eigh chain. The mask (when given) is THIS round's mask
    — a worker drop takes effect in the same round's fold and at the
    next merge, never ``s`` steps late (§5.3 under ``merge_interval``).
    """
    from distributed_eigenspaces_tpu.parallel.topology import (
        resolve_topology,
    )

    k, s = cfg.k, cfg.merge_interval
    topology = resolve_topology(cfg)
    knobs = _merge_knobs(cfg)

    def update_p(st, p):
        return update_state_projector(
            st, p, discount=cfg.discount, num_steps=cfg.num_steps
        )

    def fold_round(st, vs, vp, mask=None):
        merge_now = (st.step % s) == 0

        def do_merge(vs_):
            # merge rounds run the (possibly tiered) merge; fold-only
            # rounds below stay the FLAT masked mean — the mean of
            # projectors is associative over the tree, so the fold is
            # exact regardless of topology (only the truncating
            # eigensolve has a tree structure)
            v = merge_core(vs_, k, mask=mask, topology=topology, **knobs)
            return v, projector(v)

        def fold_only(vs_):
            return vp, mean_projector(vs_, mask)

        v_new, p = jax.lax.cond(merge_now, do_merge, fold_only, vs)
        return update_p(st, p), v_new, merge_now

    return fold_round


def _masked_body_factory(cfg, round_core, warm_core, axis_name, update):
    """ONE uniform masked step body shared by the masked scan and
    segmented programs: per-step cold-vs-warm dispatch on the carry
    itself (``lax.cond`` on "has any live round happened"), so a
    killed-and-resumed masked run is bit-for-bit the unkilled one and an
    all-masked FIRST round recovers instead of freezing a zero basis
    (zeros are a fixed point of the warm solver). Semantics are the
    per-step masked loop's exactly (tested equivalence): every round
    folds its merge result — zeros on an all-masked round — and the warm
    carry keeps the last LIVE basis.

    With ``cfg.merge_interval > 1`` the body dispatches a second
    on-device cond per round (:func:`_merge_or_fold_factory`): merge
    rounds fold the merged projector, rounds between fold the masked
    mean projector, and the warm carry updates only on LIVE merge
    rounds. At ``s = 1`` this factory returns the EXACT pre-interval
    body — the chaos/kill-resume guarantees ride on that program being
    byte-identical.
    """
    warm = warm_core is not None
    s_int = cfg.merge_interval

    if s_int == 1:

        def body(carry, x, mk):
            st, vp = carry
            if warm:
                live = jnp.any(vp != 0)
                v_bar = jax.lax.cond(
                    live,
                    lambda xx, mm, vv: warm_core(
                        xx, axis_name=axis_name, v0=vv, mask=mm
                    ),
                    lambda xx, mm, vv: round_core(
                        xx, axis_name=axis_name, mask=mm
                    ),
                    x, mk, vp,
                )
            else:
                v_bar = round_core(x, axis_name=axis_name, mask=mk)
            # liveness from the MASK row, not the merged result: the
            # per-step loop reads the mask on the host (algo/online.py),
            # and a LIVE round whose data happens to be all-zero merges
            # to an exactly zero v_bar — deriving liveness from v_bar
            # would diverge from the per-step semantics in that
            # degenerate case (ADVICE.md r5)
            vp_next = jnp.where(jnp.any(mk != 0), v_bar, vp)
            return (update(st, v_bar), vp_next), v_bar

        return body

    # merge-interval (s > 1) masked body: solve every round (cold until
    # a LIVE merge has seeded the carry, warm after), then the shared
    # merge-or-fold dispatch with THIS round's mask
    solve_cold = make_solve_core(cfg)
    solve_warm = make_warm_solve_core(cfg)
    fold_round = _merge_or_fold_factory(cfg)

    def body(carry, x, mk):
        st, vp = carry
        if warm:
            live = jnp.any(vp != 0)
            vs = jax.lax.cond(
                live,
                lambda xx, vv: solve_warm(xx, axis_name=axis_name, v0=vv),
                lambda xx, vv: solve_cold(xx, axis_name=axis_name),
                x, vp,
            )
        else:
            vs = solve_cold(x, axis_name=axis_name)
        st, v_new, merge_now = fold_round(st, vs, vp, mask=mk)
        # the warm carry advances only on LIVE merge rounds (an
        # all-masked merge yields zeros — a fixed point of the warm
        # solver; fold-only rounds produce no merged basis at all)
        vp_next = jnp.where(
            jnp.logical_and(merge_now, jnp.any(mk != 0)), v_new, vp
        )
        return (st, vp_next), v_new

    return body


def make_masked_step_body(cfg, round_core, warm_core, axis_name, update):
    """Public name of the masked per-step scan body
    (:func:`_masked_body_factory`) for trainers OUTSIDE this module: the
    fleet trainer (``parallel/fleet.py``) vmaps this exact body over the
    tenant axis, so fleet-vs-solo §5.3 equivalence is equivalence of ONE
    definition — a mask-semantics change here changes both trainers or
    neither. (Under ``vmap`` the body's ``lax.cond`` cold/warm dispatch
    lowers to ``select`` — both branches execute per tenant — which
    keeps the cond's VALUES exactly and is why the masked fleet program
    is the fault path, not the throughput path.)"""
    return _masked_body_factory(cfg, round_core, warm_core, axis_name, update)


def _make_interval_fit(cfg: PCAConfig, axis_name, update, gather: bool):
    """Unmasked whole-fit body for ``cfg.merge_interval > 1`` (pipeline
    off): every round solves (warm from the carried last-merged basis
    once one exists) and the shared merge-or-fold dispatch runs the
    merged eigensolve only on merge rounds. ``v_bars[t]`` is the merged
    basis AS OF step ``t+1`` (the carry on fold rounds)."""
    from distributed_eigenspaces_tpu.parallel.topology import (
        resolve_topology,
    )

    solve_cold = make_solve_core(cfg)
    solve_warm = make_warm_solve_core(cfg)
    warm = solve_warm is not None
    fold_round = _merge_or_fold_factory(cfg)
    k = cfg.k
    topology = resolve_topology(cfg)
    knobs = _merge_knobs(cfg)

    def body(carry, x):
        st, vp = carry
        vs = (
            solve_warm(x, axis_name=axis_name, v0=vp) if warm
            else solve_cold(x, axis_name=axis_name)
        )
        st, v_new, _ = fold_round(st, vs, vp)
        return (st, v_new), v_new

    if warm:
        # step 1: cold at the full iteration count, always merged (it
        # seeds the warm carry; also the resume-safe path)
        def run(state, first_x, scan_body, xs_rest):
            v0_bar = merge_core(
                solve_cold(first_x, axis_name=axis_name), k,
                topology=topology, **knobs,
            )
            state = update(state, v0_bar)
            (state, _), v_bars = jax.lax.scan(
                scan_body, (state, v0_bar), xs_rest
            )
            return state, jnp.concatenate([v0_bar[None], v_bars], axis=0)

        if gather:

            def fit(state, blocks, idx):
                def b(carry, i):
                    return body(carry, blocks[i])

                return run(state, blocks[idx[0]], b, idx[1:])

            return fit

        def fit(state, x_steps):
            return run(state, x_steps[0], body, x_steps[1:])

        return fit

    # all-cold interval fit: one uniform body (step 1 merges because
    # st.step % s == 0 at st.step = 0)
    def run_cold(state, scan_body, xs):
        vp0 = jnp.zeros((cfg.dim, k), jnp.float32)
        (state, _), v_bars = jax.lax.scan(scan_body, (state, vp0), xs)
        return state, v_bars

    if gather:

        def fit_cold(state, blocks, idx):
            def b(carry, i):
                return body(carry, blocks[i])

            return run_cold(state, b, idx)

        return fit_cold

    def fit_cold(state, x_steps):
        return run_cold(state, body, x_steps)

    return fit_cold


def _make_pipelined_fit(cfg: PCAConfig, axis_name, update, gather: bool):
    """The software-pipelined steady state (``cfg.pipeline_merge``): one
    scan body computes the latency-bound merge-or-fold of step ``t-1``'s
    PENDING factors AND step ``t``'s warm worker solves from the
    one-step-STALE merged basis (merges through step ``t-2``). The two
    are data-independent inside one program, so XLA's scheduler can
    overlap the serial merge/fold chain with the next round's MXU work
    instead of serializing with it — the carry holds ``(state,
    pending_factors, stale_basis)`` instead of ``(state, v_prev)``.

    Schedule: step 1 runs cold and merges unpipelined (it seeds the
    carry); step 2's solves use step 1's fresh merge (there is nothing
    staler yet); steps >= 3 are fully pipelined; an epilogue merges/
    folds the final pending round. Composes with ``merge_interval`` (the
    pending fold dispatches through :func:`_merge_or_fold_factory`, same
    phase schedule as the unpipelined interval fit). Requires warm
    starts (config-validated): the stale carry IS a warm-start lever.
    """
    solve_cold = make_solve_core(cfg)
    solve_warm = make_warm_solve_core(cfg)
    fold_round = _merge_or_fold_factory(cfg)
    k = cfg.k

    def fold_pending(st, vs_p, vp):
        st, v_new, _ = fold_round(st, vs_p, vp)
        return st, v_new

    def body(carry, x):
        st, vs_p, vp = carry
        # this round's solves read the STALE carry vp — independent of
        # fold_pending's outputs, which is the whole point
        vs = solve_warm(x, axis_name=axis_name, v0=vp)
        st, v_new = fold_pending(st, vs_p, vp)
        return (st, vs, v_new), v_new

    def run(state, get, T, scan_body, xs_scan):
        # prologue: cold step 1, merged + folded before any pipelining
        v1 = merge_core(
            solve_cold(get(0), axis_name=axis_name), k,
            **_merge_knobs(cfg),
        )
        state = update(state, v1)
        if T == 1:
            return state, v1[None]
        # prime: step 2's solves from step 1's fresh merge
        vs = solve_warm(get(1), axis_name=axis_name, v0=v1)
        carry = (state, vs, v1)
        ys = None
        if T > 2:
            carry, ys = jax.lax.scan(scan_body, carry, xs_scan)
        state, vs_p, vp = carry
        # epilogue: the final pending round's merge-or-fold
        state, v_last = fold_pending(state, vs_p, vp)
        parts = [v1[None]]
        if ys is not None:
            parts.append(ys)
        parts.append(v_last[None])
        return state, jnp.concatenate(parts, axis=0)

    if gather:

        def fit(state, blocks, idx):
            T = int(idx.shape[0])

            def b(carry, i):
                return body(carry, blocks[i])

            return run(
                state, lambda t: blocks[idx[t]], T, b,
                idx[2:] if T > 2 else None,
            )

        return fit

    def fit(state, x_steps):
        T = int(x_steps.shape[0])
        return run(
            state, lambda t: x_steps[t], T, body,
            x_steps[2:] if T > 2 else None,
        )

    return fit


def make_scan_fit(
    cfg: PCAConfig, mesh: Mesh | None = None, *, gather: bool = False,
    masked: bool = False,
):
    """Build the whole-fit trainer, jitted.

    ``gather=False``: ``fit(state, x_steps) -> (state, v_bars)`` where
    ``x_steps`` is ``(T, m, n, d)`` — T online steps of m-worker blocks;
    ``v_bars`` is ``(T, d, k)``, the merged eigenspace after every step.

    ``gather=True``: ``fit(state, blocks, idx) -> (state, v_bars)`` where
    ``blocks`` is ``(B, m, n, d)`` distinct staged blocks and ``idx`` a
    ``(T,)`` int32 schedule — each scan step gathers ``blocks[idx[t]]``
    inside the body, so device memory stays O(B) instead of O(T) (the
    cycled-blocks benchmark pattern without materializing the cycle).

    Semantically identical to calling the per-step trainer T times (tested —
    both build on :func:`~..algo.step.make_round_core`), just compiled as
    one program.

    With ``cfg.warm_start_iters`` set (subspace solver only), the first
    step runs the full-iteration cold core and every later step warm-starts
    its per-worker solves from the previous merged ``v_bar`` with the short
    iteration count — the online-stream optimization BASELINE.md measures.

    ``masked=True`` builds the §5.3 fault-exclusion variant instead:
    ``fit(state, x_steps, masks) -> (state, v_bars)`` with ``masks`` a
    ``(T, m)`` {0,1} array — one program, per-step cold/warm dispatch on
    the carry (:func:`_masked_body_factory`), equivalent to the per-step
    masked loop (tested). The unmasked build stays the exact pre-mask
    program, so the throughput path pays nothing for the fault
    machinery. ``gather`` staging is not offered masked (masked fits are
    dense-staged by the estimator).

    Steady-state restructures (docs/ARCHITECTURE.md "Steady-state
    pipeline"): ``cfg.merge_interval = s > 1`` runs the merged
    eigensolve every s steps and folds the mean worker projector
    between merges (:func:`_make_interval_fit` /
    :func:`_merge_or_fold_factory`); ``cfg.pipeline_merge`` additionally
    overlaps step ``t-1``'s merge/fold with step ``t``'s warm solves
    from a one-step-stale basis (:func:`_make_pipelined_fit`). With both
    knobs at their defaults (``s=1``, pipeline off) the build dispatches
    to the UNCHANGED pre-knob code path — bit for bit. Masked fits honor
    ``merge_interval`` but run unpipelined (``pipeline_merge`` is
    ignored there — the fault path is not the throughput path; the
    drop-at-next-merge timing is the tested contract).
    """
    # function-level import: utils.__init__ pulls checkpoint, which
    # imports this module — a top-level import would cycle
    from distributed_eigenspaces_tpu.utils.guards import checked_jit

    if masked and gather:
        raise ValueError("masked scan fits take a dense (T, ...) stack")

    # tiered-mesh dispatch: a mesh whose axes ARE the topology's tiers
    # runs the tier-local-collective programs (parallel/topology.py —
    # no factor gather, sharded tier updates). Any other build with a
    # topology set (single device, single worker axis) runs the stacked
    # tree through round_core/merge_core below; no topology at all is
    # the byte-identical pre-topology build.
    from distributed_eigenspaces_tpu.parallel.topology import (
        is_tiered_mesh,
        make_tree_scan_fit,
        resolve_topology,
    )

    if is_tiered_mesh(mesh, resolve_topology(cfg)):
        if gather:
            raise ValueError(
                "gather staging is not supported on the tiered-mesh "
                "path (stage dense (T, ...) stacks, or use a flat "
                "worker-axis mesh)"
            )
        return make_tree_scan_fit(cfg, mesh, masked=masked)

    round_core = make_round_core(cfg)
    warm_core = make_warm_core(cfg)
    warm = warm_core is not None

    def make_fit(axis_name):
        def update(st, v_bar):
            return update_state(
                st, v_bar, discount=cfg.discount, num_steps=cfg.num_steps
            )

        if masked:
            mbody = _masked_body_factory(
                cfg, round_core, warm_core, axis_name, update
            )

            def fit_masked(state, x_steps, masks):
                k = cfg.k
                vp0 = jnp.zeros((cfg.dim, k), jnp.float32)
                (state, _), v_bars = jax.lax.scan(
                    lambda c, xm: mbody(c, xm[0], xm[1]),
                    (state, vp0),
                    (x_steps, masks.astype(jnp.float32)),
                )
                return state, v_bars

            return fit_masked

        if cfg.pipeline_merge:
            return _make_pipelined_fit(cfg, axis_name, update, gather)
        if cfg.merge_interval > 1:
            return _make_interval_fit(cfg, axis_name, update, gather)

        def step_body(st, x):
            v_bar = round_core(x, axis_name=axis_name)
            return update(st, v_bar), v_bar

        def warm_body(carry, x):
            st, v_prev = carry
            v_bar = warm_core(x, axis_name=axis_name, v0=v_prev)
            return (update(st, v_bar), v_bar), v_bar

        def warm_fit(first_x, scan_body, xs_rest, state):
            # step 1: cold, full iterations (also the resume-safe path:
            # no solver state is assumed to exist)
            v0_bar = round_core(first_x, axis_name=axis_name)
            state = update(state, v0_bar)
            (state, _), v_bars = jax.lax.scan(
                scan_body, (state, v0_bar), xs_rest
            )
            return state, jnp.concatenate([v0_bar[None], v_bars], axis=0)

        if warm and gather:

            def fit(state, blocks, idx):
                def body(carry, i):
                    return warm_body(carry, blocks[i])

                return warm_fit(blocks[idx[0]], body, idx[1:], state)

            return fit

        if warm:

            def fit(state, x_steps):
                return warm_fit(
                    x_steps[0], warm_body, x_steps[1:], state
                )

            return fit

        if gather:

            def fit_gather(state, blocks, idx):
                def body(st, i):
                    return step_body(st, blocks[i])

                return jax.lax.scan(body, state, idx)

            return fit_gather

        def fit_dense(state, x_steps):
            return jax.lax.scan(step_body, state, x_steps)

        return fit_dense

    if mesh is None:
        # checked_jit == jax.jit unless DET_CHECKIFY=1 (NaN guards, §5.2)
        fitted = checked_jit(make_fit(axis_name=None))
    else:
        # one shard_map around the whole scan: the worker axis stays
        # device-resident across all T steps and only the k-width merge
        # crosses ICI each step
        rep = NamedSharding(mesh, P())
        x_sharding = NamedSharding(mesh, P(None, WORKER_AXIS))
        extra = (P(),) if (gather or masked) else ()  # idx / (T, m) masks
        in_specs = (P(), P(None, WORKER_AXIS)) + extra
        in_shardings = (rep, x_sharding) + (
            (rep,) if (gather or masked) else ()
        )
        inner = shard_map(
            make_fit(axis_name=WORKER_AXIS),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(), P()),
            check_vma=False,
        )
        fitted = checked_jit(
            inner, in_shardings=in_shardings, out_shardings=(rep, rep)
        )
    if not masked:
        return fitted

    def fit_masked_elastic(state, x_steps, masks, membership_masks=None):
        """Masked whole-fit entry with the elastic-membership mask
        threaded exactly like the worker mask (ISSUE 8): a recorded
        elastic run's ``(T, m)`` per-round membership masks
        (``summary()["membership"]`` / ``ElasticStream``) compose
        multiplicatively with the quarantine masks BEFORE the program
        — membership ∧ quarantine is the same masked mean, so elastic
        runs replay through the unchanged compiled masked program
        (scan-compatible by construction)."""
        if membership_masks is not None:
            masks = jnp.asarray(masks, jnp.float32) * jnp.asarray(
                membership_masks, jnp.float32
            )
        return fitted(state, x_steps, masks)

    return fit_masked_elastic


class SegmentState(NamedTuple):
    """Checkpointable carry of the segmented scan trainer: the online state
    PLUS the warm-start carry (the last merged estimate), so a resumed run
    continues bit-for-bit — without ``v_prev`` the first post-resume step
    would have to run cold and diverge from the unkilled run.
    """

    sigma_tilde: jax.Array
    step: jax.Array  # int32 scalar, 1-based rounds folded in
    v_prev: jax.Array  # (d, k) last merged estimate; zeros before step 1

    @classmethod
    def initial(cls, dim: int, k: int, dtype=jnp.float32) -> "SegmentState":
        return cls(
            sigma_tilde=jnp.zeros((dim, dim), dtype=dtype),
            step=jnp.zeros((), jnp.int32),
            v_prev=jnp.zeros((dim, k), dtype=jnp.float32),
        )


def make_segmented_fit(cfg: PCAConfig, mesh: Mesh | None = None, *,
                       segment: int = 50):
    """Checkpointable whole-fit trainer: T steps run as ceil(T/S)
    ``lax.scan`` programs of S steps each, with a host hook between
    segments — ``fit(state, x_steps, on_segment=None) -> SegmentState``.

    This closes the round-1 gap "the fastest trainer can't checkpoint":
    per-segment dispatch costs 1/S of the per-step trainer's (S=50 keeps
    it ~2% on the tunneled dev host), while ``on_segment(steps_done,
    state)`` runs on the host between programs for checkpoint/metrics
    (utils/checkpoint.py saves ``SegmentState`` like any other state).

    Semantics are identical to :func:`make_scan_fit` on the same workload
    (same ``make_round_core``; with ``cfg.warm_start_iters`` the cold
    first step runs only when ``state.step == 0``, and the warm carry
    crosses segment AND checkpoint boundaries via ``state.v_prev``) —
    a killed-and-resumed run is bit-for-bit the unkilled run.

    ``x_steps`` may be a host array: each segment's slice is transferred
    as its program runs (O(S) device memory, not O(T)).

    ``cfg.merge_interval > 1`` is honored resume-safely: the merge
    phase derives from the on-device step counter (part of every
    checkpoint), so a killed-and-resumed run re-enters the interval at
    the right phase and stays bit-for-bit. ``cfg.pipeline_merge`` is
    REJECTED here: the pipelined carry holds a pending (m, d, k) factor
    stack that is not part of ``SegmentState``, so a kill between
    segments could not resume bit-for-bit — use the one-program scan
    trainer for pipelined fits, or ``merge_interval`` alone for a
    checkpointable steady-state win.
    """
    if segment < 1:
        raise ValueError(f"segment must be >= 1, got {segment}")
    if cfg.pipeline_merge:
        raise ValueError(
            "pipeline_merge is not supported by the segmented trainer: "
            "the pending-factor carry is not checkpointable state, so "
            "kill/resume could not be bit-for-bit (use make_scan_fit, "
            "or merge_interval without pipelining)"
        )
    from distributed_eigenspaces_tpu.utils.guards import checked_jit

    round_core = make_round_core(cfg)
    warm_core = make_warm_core(cfg)
    warm = warm_core is not None
    s_int = cfg.merge_interval

    def update(st, v_bar):
        return update_state(
            st, v_bar, discount=cfg.discount, num_steps=cfg.num_steps
        )

    def make_seg(axis_name, first):
        if s_int > 1:
            solve_cold = make_solve_core(cfg)
            solve_warm = make_warm_solve_core(cfg)
            fold_round = _merge_or_fold_factory(cfg)

            def body(carry, x):
                st, vp = carry
                vs = (
                    solve_warm(x, axis_name=axis_name, v0=vp) if warm
                    else solve_cold(x, axis_name=axis_name)
                )
                st, v_new, _ = fold_round(st, vs, vp)
                return (st, v_new), None

        else:
            core = warm_core if warm else round_core

            def body(carry, x):
                st, vp = carry
                v = (
                    core(x, axis_name=axis_name, v0=vp) if warm
                    else core(x, axis_name=axis_name)
                )
                return (update(st, v), v), None

        def seg(sstate, x_steps):
            st = OnlineState(sstate.sigma_tilde, sstate.step)
            vp = sstate.v_prev
            if warm and first:
                # cold first step at the full iteration count
                vp = round_core(x_steps[0], axis_name=axis_name)
                st = update(st, vp)
                x_steps = x_steps[1:]
            (st, vp), _ = jax.lax.scan(body, (st, vp), x_steps)
            return SegmentState(st.sigma_tilde, st.step, vp)

        return seg

    def make_seg_masked(axis_name):
        """§5.3 masked window program — ONE program for every window,
        first or continuation: per-step cold/warm dispatch on the carry
        (:func:`_masked_body_factory`), so kill/resume is bit-for-bit
        and an all-masked first round recovers cold."""
        mbody = _masked_body_factory(
            cfg, round_core, warm_core, axis_name, update
        )

        def body(c, xm):
            carry, _ = mbody(c, xm[0], xm[1])
            return carry, None

        def seg(sstate, x_steps, masks):
            st = OnlineState(sstate.sigma_tilde, sstate.step)
            (st, vp), _ = jax.lax.scan(
                body,
                (st, sstate.v_prev),
                (x_steps, masks.astype(jnp.float32)),
            )
            return SegmentState(st.sigma_tilde, st.step, vp)

        return seg

    if mesh is None:
        def build(first):
            return checked_jit(make_seg(None, first))

        def build_masked():
            return checked_jit(make_seg_masked(None))
    else:
        rep = NamedSharding(mesh, P())
        x_sharding = NamedSharding(mesh, P(None, WORKER_AXIS))

        def build(first):
            inner = shard_map(
                make_seg(WORKER_AXIS, first),
                mesh=mesh,
                in_specs=(P(), P(None, WORKER_AXIS)),
                out_specs=P(),
                check_vma=False,
            )
            return checked_jit(
                inner, in_shardings=(rep, x_sharding), out_shardings=rep
            )

        def build_masked():
            inner = shard_map(
                make_seg_masked(WORKER_AXIS),
                mesh=mesh,
                in_specs=(P(), P(None, WORKER_AXIS), P()),
                out_specs=P(),
                check_vma=False,
            )
            return checked_jit(
                inner,
                in_shardings=(rep, x_sharding, rep),
                out_shardings=rep,
            )

    compiled = {}

    def _get(first, masked=False):
        key = (False, True) if masked else (first, False)
        if key not in compiled:
            compiled[key] = build_masked() if masked else build(first)
        return compiled[key]

    def fit_windows(
        state, windows, on_segment=None, worker_masks=None
    ) -> SegmentState:
        """Out-of-core variant: consume an ITERATOR of staged
        ``(S, m, n, d)`` windows instead of one resident ``(T, ...)``
        array — the whole-fit path for streams that never fit in device
        (or host) memory, e.g. the bin pipeline's 400M-row config.

        Each window runs as one S-step program; wrap the window source in
        :func:`~..runtime.prefetch.prefetch_stream` and window t+1's
        disk read + host convert + host->device transfer overlap window
        t's device program (the fit only fences at its caller's final
        value fetch). ``S`` may vary (a ragged tail window just
        specializes the jit once more); semantics are identical to
        :func:`fit` on the concatenation (same compiled programs —
        ``fit`` IS this function over a slice generator).

        ``worker_masks`` (an iterable of ``(S, m)`` {0,1} arrays
        parallel to ``windows``, zipped strict) runs the §5.3 masked
        window program instead — one cond-dispatch program for every
        window, so kill/resume stays bit-for-bit (the per-step
        cold/warm branch depends only on the restored carry).
        """
        # without warm start the "first" program is identical to the
        # continuation program — never compile it twice. A ZERO carry
        # must also run cold: zeros are a fixed point of the warm
        # solver (orth(0) = 0), so warm-starting from a restored state
        # that lacks v_prev (cross-trainer resume) would silently
        # discard every subsequent step. Evaluated once up front: after
        # the first window ``step > 0`` and ``v_prev`` is nonzero, so
        # re-fetching these scalars per window would pay two blocking
        # device->host round trips for a value that can only be False.
        first = warm and (
            int(state.step) == 0 or not bool(jnp.any(state.v_prev))
        )
        pairs = (
            ((w, None) for w in windows)
            if worker_masks is None
            else zip(windows, worker_masks, strict=True)
        )
        for w, mk in pairs:
            if mk is None:
                state = _get(first)(state, w)
            else:
                state = _get(first, masked=True)(
                    state, w, jnp.asarray(mk, jnp.float32)
                )
            first = False
            if on_segment is not None:
                on_segment(int(state.step), state)
        return state

    def fit(state: SegmentState, x_steps, on_segment=None) -> SegmentState:
        total = x_steps.shape[0]
        return fit_windows(
            state,
            (
                jnp.asarray(x_steps[t : t + segment])
                for t in range(0, total, segment)
            ),
            on_segment,
        )

    fit.segment = segment
    fit.fit_windows = fit_windows
    return fit
