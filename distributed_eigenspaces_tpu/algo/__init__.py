"""Algorithm layer: the online distributed PCA outer loop and one-shot round.

Implements the pseudocode at reference ``assets/algorithm.png`` (notebook cell
12) exactly — unlike the reference, which diverges in the AMQP path (single
round, result discarded — SURVEY.md §2.2-B4) and the notebook (static data,
wrong discount — §2.2-B6).
"""

from distributed_eigenspaces_tpu.algo.online import (
    online_distributed_pca,
    one_shot_round,
    OnlineState,
)

__all__ = ["online_distributed_pca", "one_shot_round", "OnlineState"]
