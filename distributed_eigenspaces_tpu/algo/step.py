"""Fused training step: one whole algorithm round + online update as ONE jit.

The reference spreads a single round over four processes and a broker
(slave compute ``distributed.py:46-52``, wire hop, master merge
``distributed.py:126-131``, and the notebook's separate running-average line,
cell 16). Here the entire round — per-worker Gram + eigensolve, the ICI
allreduce of projectors, the merged eigensolve, and the sigma_tilde update —
is a single XLA program, so the compiler fuses across what used to be process
boundaries and nothing leaves the device between steps.

This is the function the benchmark times and ``__graft_entry__`` exposes.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_eigenspaces_tpu.algo.online import (
    OnlineState,
    update_state,
    update_state_projector,
)
from distributed_eigenspaces_tpu.config import PCAConfig
from distributed_eigenspaces_tpu.parallel.mesh import WORKER_AXIS, shard_map
from distributed_eigenspaces_tpu.parallel.worker_pool import (
    _local_eigenspaces,
    _masked_projector_mean,
)
from distributed_eigenspaces_tpu.ops.linalg import merged_top_k_lowrank


def make_solve_core(
    cfg: PCAConfig, iters: int | None = None, orth: str | None = None
):
    """The SOLVE+GATHER half of a round: ``solve_core(x_blocks,
    axis_name=None, v0=None) -> vs (m, d, k)`` — per-worker local
    eigenspaces plus the cross-device factor gather, WITHOUT the merge.

    The pipelined / merge-interval steady states (``cfg.pipeline_merge``
    / ``cfg.merge_interval``) compose rounds from this half plus
    :func:`merge_core` / :func:`mean_projector` so the merge can move
    relative to the solves; :func:`make_round_core` composes the same
    halves back into the classic fused round, so the numerics have ONE
    definition either way.
    """
    # "distributed" runs the subspace machinery for LOCAL solves; its
    # crossover dispatch lives in the MERGE (merge_core / extract)
    k, solver = cfg.k, cfg.resolved_local_solver()
    if iters is None:
        iters = cfg.subspace_iters
    # ``orth`` override: warm cores pass cfg.resolved_warm_orth() (the
    # "ns" steady state is warm-only — see PCAConfig.warm_orth_method)
    if orth is None:
        orth = cfg.orth_method
    cdtype = cfg.compute_dtype

    # profiler annotation (§5.1): these named regions are the units a
    # captured trace shows — worker solve vs gather vs merge
    from distributed_eigenspaces_tpu.utils.tracing import named_scope

    def solve_core(x_blocks, axis_name=None, v0=None):
        with named_scope("det_worker_solve"):
            vs = _local_eigenspaces(
                x_blocks, k, solver, iters, orth, cdtype, v0
            )
        if axis_name is not None:
            # the entire reference wire protocol (C11) is this one gather
            # of d x k factors — m*d*k floats over ICI, vs the d*d psum a
            # dense merge would need
            with named_scope("det_factor_gather"):
                vs = jax.lax.all_gather(vs, axis_name, axis=0, tiled=True)
        return vs

    return solve_core


def make_warm_solve_core(cfg: PCAConfig):
    """Warm-parameterized :func:`make_solve_core` (short iteration count
    + warm orthonormalization), or None when warm starts are off — the
    solve-only twin of :func:`make_warm_core`."""
    warm_iters = cfg.resolved_warm_start()
    if warm_iters is None:
        return None
    return make_solve_core(
        cfg, iters=warm_iters, orth=cfg.resolved_warm_orth()
    )


def merge_core(vs, k, mask=None, topology=None, dist_iters=None,
               deflate_lanes=None, dist_tol=None):
    """The MERGE half of a round: exact masked low-rank top-k of the
    gathered factors (``merged_top_k_lowrank``), under the profiler
    region the traces name. ``mask`` (full ``(m,)`` {0,1}, replicated)
    excludes failed workers exactly; an all-masked round merges to
    zeros. ``topology`` (a resolved
    :class:`~..parallel.topology.MergeTopology`) runs the tiered tree
    reduce over the stack instead (``tree_merge_stacked`` — per-group
    exact merges, live-count weighted); ``None`` is the byte-identical
    flat merge. ``dist_iters`` (set when
    ``cfg.uses_distributed_solve()`` — solver="distributed" above the
    ``eigh_crossover_d`` crossover) swaps the merge eigensolve for the
    distributed subspace path (``solvers/``): the flat merge solves
    the factor operator iteratively instead of the ``(m*k)^2`` Gram /
    dense-route eigh, and a tiered tree applies it at the ROOT tier
    only (lower tiers' per-group problems are small by
    construction). ``deflate_lanes`` (set when
    ``cfg.uses_deflation_solve()`` — solver="deflation" above the
    crossover, ISSUE 18) swaps the crossover merge for the
    PARALLEL-DEFLATION lanes instead: ``cfg.components_axis_size``
    concurrent eigenvector lanes on the same factor operator.
    ``dist_tol`` (``cfg.solver_tol``) arms the gap-adaptive stop on
    either crossover route."""
    from distributed_eigenspaces_tpu.utils.tracing import named_scope

    if topology is not None:
        from distributed_eigenspaces_tpu.parallel.topology import (
            tree_merge_stacked,
        )

        with named_scope("det_tree_merge"):
            return tree_merge_stacked(
                vs, k, topology, mask=mask, root_dist_iters=dist_iters
            )
    if dist_iters is not None:
        if deflate_lanes is not None:
            from distributed_eigenspaces_tpu.solvers import (
                merged_top_k_deflation,
            )

            with named_scope("det_deflation_merge"):
                return merged_top_k_deflation(
                    vs, k, lanes=deflate_lanes, mask=mask,
                    iters=dist_iters, tol=dist_tol,
                )
        from distributed_eigenspaces_tpu.solvers import (
            merged_top_k_distributed,
        )

        with named_scope("det_dist_merge"):
            return merged_top_k_distributed(
                vs, k, mask=mask, iters=dist_iters, tol=dist_tol,
            )
    with named_scope("det_merge"):
        return merged_top_k_lowrank(vs, k, mask=mask)


def mean_projector(vs, mask=None):
    """Masked MEAN of the worker projectors ``(1/Σw) Σ w_l V_l V_lᵀ``
    from the gathered ``(m, d, k)`` factors — what the merge-interval
    steady state folds on the steps between merges (``sigma_bar``, the
    same quantity ``WorkerPool.round`` exposes). An all-masked round
    yields zeros (callers fold the zero projector — the tested §5.3
    semantics)."""
    from distributed_eigenspaces_tpu.utils.tracing import named_scope

    if mask is None:
        mask = jnp.ones((vs.shape[0],), jnp.float32)
    with named_scope("det_mean_projector"):
        psum, cnt = _masked_projector_mean(vs, mask)
        return psum / jnp.maximum(cnt, 1.0)


def make_round_core(
    cfg: PCAConfig, iters: int | None = None, orth: str | None = None
):
    """Shared per-round compute: ``round_core(x_blocks, axis_name=None,
    v0=None) -> v_bar``.

    The single definition of "one algorithm round" (local eigenspaces ->
    cross-device ``all_gather`` of the (m, d, k) factors -> exact low-rank
    merged top-k, :func:`~..ops.linalg.merged_top_k_lowrank`) used by both
    the per-step trainer here and the whole-fit scan trainer (algo/scan.py),
    so solver/merge changes can't diverge between them — composed from
    :func:`make_solve_core` + :func:`merge_core` since the pipelined
    restructure, so the split cores and the fused round cannot drift.
    The d x d mean projector is never materialized on this path (the
    WorkerPool.round API still exposes it). ``axis_name`` names the mesh
    axis to gather over (None = single device). ``iters`` overrides
    ``cfg.subspace_iters`` (the warm-start trainer uses a
    short-iteration core for steps > 0); ``v0`` warm-starts the
    per-worker subspace iterations. ``mask`` (full ``(m,)`` {0,1},
    replicated) excludes failed workers from the merge — the §5.3 fault
    exclusion, weighted exactly
    (:func:`~..ops.linalg.merged_top_k_lowrank`); an all-masked round
    merges to zeros (callers fold the zero projector and keep their
    warm carry — the per-step loop's tested semantics).
    """
    # resolved ONCE at build time: cfg.merge_topology = None threads
    # topology=None straight through merge_core — the traced program is
    # byte-identical to the pre-topology build (the merge_interval
    # discipline). Function-level import: parallel.topology imports
    # ops.linalg only, but keep the build path lazy like the tracing
    # imports above.
    from distributed_eigenspaces_tpu.parallel.topology import (
        resolve_topology,
    )

    topology = resolve_topology(cfg)
    solve_core = make_solve_core(cfg, iters=iters, orth=orth)
    k = cfg.k
    dist_iters = cfg.subspace_iters if cfg.uses_distributed_solve() else None
    deflate_lanes = (
        cfg.components_axis_size
        if (dist_iters is not None and cfg.uses_deflation_solve())
        else None
    )
    dist_tol = cfg.solver_tol if dist_iters is not None else None

    def round_core(x_blocks, axis_name=None, v0=None, mask=None):
        vs = solve_core(x_blocks, axis_name=axis_name, v0=v0)
        return merge_core(
            vs, k, mask=mask, topology=topology, dist_iters=dist_iters,
            deflate_lanes=deflate_lanes, dist_tol=dist_tol,
        )

    return round_core


def make_warm_core(cfg: PCAConfig):
    """The warm-round core, or None when warm starts are off — ONE
    definition of "short iteration count + warm orthonormalization"
    (``resolved_warm_start`` / ``resolved_warm_orth``) for every
    warm-core build site (per-step / scan / segmented), so a future
    warm knob threads through one place and the tested trainer
    equivalences cannot drift."""
    warm_iters = cfg.resolved_warm_start()
    if warm_iters is None:
        return None
    return make_round_core(
        cfg, iters=warm_iters, orth=cfg.resolved_warm_orth()
    )


def make_train_step(
    cfg: PCAConfig, mesh: Mesh | None = None, *, donate: bool = True
):
    """Build ``step(state, x_blocks, v_prev=None, merge=True) ->
    (state, v_bar)``, jitted.

    ``mesh=None`` gives the single-device (vmap-over-workers) step;
    with a mesh, worker compute runs under ``shard_map`` over the
    ``workers`` axis, the merge is a ``psum`` over ICI, and the returned
    state/eigenspace are replicated.

    With ``cfg.warm_start_iters`` set (subspace solver), passing ``v_prev``
    — the previous round's merged eigenspace — runs the short
    warm-started solver core instead of the full-iteration cold core:
    the per-step/streaming trainers get the same online warm-start lever
    the scan trainer has (callers thread the returned ``v_bar`` back in).
    Without ``v_prev`` (or without the config knob) every step runs cold.

    With ``cfg.merge_interval > 1``, ``merge=False`` runs the
    FOLD-ONLY executables for the steps between merges: same solves,
    then the masked-free mean projector folded directly — no
    ``merged_top_k_lowrank``, no k-wide eigh chain in the program at
    all. The return is ``(state, v_prev)`` (the carry is unchanged — a
    fold round produces no new merged basis); callers schedule the
    phase (``merge = ((t - 1) % s == 0)``). ``cfg.pipeline_merge`` does
    not change this per-step builder — the pipelined carry restructure
    lives in the whole-fit scan trainer (``algo/scan.py``), where the
    merge and the next step's solves share one program.

    ``donate=True`` donates the state argument (reuses the d*d buffer —
    right for training loops that thread the state). Pass ``donate=False``
    if the same state object will be passed again (e.g. repeated timing
    calls on fixed example args).
    """
    from distributed_eigenspaces_tpu.utils.guards import checked_jit

    round_core = make_round_core(cfg)
    warm_core = make_warm_core(cfg)
    warm = warm_core is not None
    donate_args = (0,) if donate else ()
    s_int = cfg.merge_interval

    def fold(state, v_bar):
        return (
            update_state(
                state, v_bar, discount=cfg.discount, num_steps=cfg.num_steps
            ),
            v_bar,
        )

    def fold_p(state, p):
        return update_state_projector(
            state, p, discount=cfg.discount, num_steps=cfg.num_steps
        )

    # fold-only executables (merge-interval steps between merges) are
    # built lazily below ONLY when cfg.merge_interval > 1 — the default
    # path compiles exactly the pre-knob programs
    solve_cold = make_solve_core(cfg) if s_int > 1 else None
    solve_warm = make_warm_solve_core(cfg) if s_int > 1 else None

    # checked_jit == jax.jit unless DET_CHECKIFY=1 arms the §5.2 NaN/inf
    # guards (resolved here, at build time)
    if mesh is None:

        def cold_fn(state: OnlineState, x_blocks):
            return fold(state, round_core(x_blocks))

        cold = checked_jit(cold_fn, donate_argnums=donate_args)

        if warm:

            def warm_fn(state: OnlineState, x_blocks, v_prev):
                return fold(state, warm_core(x_blocks, v0=v_prev))

            warm_step = checked_jit(warm_fn, donate_argnums=donate_args)

        if s_int > 1:
            cold_fold = checked_jit(
                lambda state, x: fold_p(
                    state, mean_projector(solve_cold(x))
                ),
                donate_argnums=donate_args,
            )
            if warm:
                warm_fold = checked_jit(
                    lambda state, x, v_prev: fold_p(
                        state, mean_projector(solve_warm(x, v0=v_prev))
                    ),
                    donate_argnums=donate_args,
                )

    else:
        x_sharding = NamedSharding(mesh, P(WORKER_AXIS))
        rep = NamedSharding(mesh, P())

        # fold lives INSIDE the shard_map (replicated compute, out_specs
        # P()): checkify's error plumbing composes with
        # jit(shard_map(whole_step)) but not with float ops split across
        # the shard_map boundary (sharded vs replicated error payloads)
        state_specs = OnlineState(sigma_tilde=P(), step=P())

        inner = shard_map(
            lambda state, x: fold(
                state, round_core(x, axis_name=WORKER_AXIS)
            ),
            mesh=mesh,
            in_specs=(state_specs, P(WORKER_AXIS)),
            out_specs=(state_specs, P()),
            check_vma=False,
        )
        cold = checked_jit(
            inner,
            in_shardings=(rep, x_sharding),
            out_shardings=(rep, rep),
            donate_argnums=donate_args,
        )

        if warm:
            inner_warm = shard_map(
                lambda state, x, v0: fold(
                    state, warm_core(x, axis_name=WORKER_AXIS, v0=v0)
                ),
                mesh=mesh,
                in_specs=(state_specs, P(WORKER_AXIS), P()),
                out_specs=(state_specs, P()),
                check_vma=False,
            )
            warm_step = checked_jit(
                inner_warm,
                in_shardings=(rep, x_sharding, rep),
                out_shardings=(rep, rep),
                donate_argnums=donate_args,
            )

        if s_int > 1:
            inner_cold_fold = shard_map(
                lambda state, x: fold_p(
                    state,
                    mean_projector(solve_cold(x, axis_name=WORKER_AXIS)),
                ),
                mesh=mesh,
                in_specs=(state_specs, P(WORKER_AXIS)),
                out_specs=state_specs,
                check_vma=False,
            )
            cold_fold = checked_jit(
                inner_cold_fold,
                in_shardings=(rep, x_sharding),
                out_shardings=rep,
                donate_argnums=donate_args,
            )
            if warm:
                inner_warm_fold = shard_map(
                    lambda state, x, v0: fold_p(
                        state,
                        mean_projector(
                            solve_warm(x, axis_name=WORKER_AXIS, v0=v0)
                        ),
                    ),
                    mesh=mesh,
                    in_specs=(state_specs, P(WORKER_AXIS), P()),
                    out_specs=state_specs,
                    check_vma=False,
                )
                warm_fold = checked_jit(
                    inner_warm_fold,
                    in_shardings=(rep, x_sharding, rep),
                    out_shardings=rep,
                    donate_argnums=donate_args,
                )

    def step(state: OnlineState, x_blocks, v_prev=None, merge=True):
        if not merge:
            if s_int == 1:
                raise ValueError(
                    "step(merge=False) needs cfg.merge_interval > 1 "
                    "(the fold-only executables are built from the "
                    "interval config)"
                )
            if warm and v_prev is not None:
                return warm_fold(state, x_blocks, v_prev), v_prev
            return cold_fold(state, x_blocks), v_prev
        if warm and v_prev is not None:
            return warm_step(state, x_blocks, v_prev)
        return cold(state, x_blocks)

    return step
