"""Online distributed PCA — the outer time loop over a worker pool.

The algorithm (reference pseudocode, ``assets/algorithm.png`` / notebook cell
12; executed prototype at notebook cell 16):

    sigma_tilde(0) = 0
    for t = 1..T:
        per worker l: V_hat_l = top-k eigvecs of (1/n) X_l^T X_l
        sigma_bar = (1/m) sum_l V_hat_l V_hat_l^T       # one gather on TPU
        v_bar = top-k eigvecs of sigma_bar
        sigma_tilde += discount * v_bar v_bar^T
    output: top-k eigvecs of sigma_tilde

Deliberate fixes over the reference (SURVEY.md §2.2):
  - B4: the final ``top_k(sigma_tilde)`` is actually computed and returned
    (the reference master discards the merge and never exits).
  - B6: the data stream *advances* every step (the notebook re-read the same
    first m batches forever), and the discount follows the pseudocode
    (``1/T``) or a true running mean (``1/t``); the notebook's buggy
    ``1/(t+1)``/T-1-step variant survives only behind ``discount="notebook"``.
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from distributed_eigenspaces_tpu.config import PCAConfig
from distributed_eigenspaces_tpu.ops.linalg import projector, top_k_eigvecs
from distributed_eigenspaces_tpu.parallel.worker_pool import WorkerPool


class OnlineState(NamedTuple):
    """Checkpointable algorithm state (SURVEY.md §5.4): tiny and complete.

    ``sigma_tilde`` is the (d, d) running projector average; ``step`` is the
    1-based count of merge rounds already folded in. Together with the data
    stream's cursor this is everything needed to resume.
    """

    sigma_tilde: jax.Array
    step: jax.Array  # int32 scalar

    @classmethod
    def initial(cls, dim: int, dtype=jnp.float32) -> "OnlineState":
        return cls(
            sigma_tilde=jnp.zeros((dim, dim), dtype=dtype),
            step=jnp.zeros((), dtype=jnp.int32),
        )


def _discount(rule: str, step: jax.Array, num_steps: int) -> jax.Array:
    """Per-step weight applied to the new projector. ``step`` is 1-based."""
    if rule == "1/T":
        return jnp.asarray(1.0 / num_steps, jnp.float32)
    if rule == "1/t":
        # running mean: sigma_tilde <- (1 - 1/t) sigma_tilde + (1/t) P
        return 1.0 / step.astype(jnp.float32)
    if rule == "notebook":
        # bug-compatible 1/(t+1) additive weight (notebook cell 16, B6)
        return 1.0 / (step.astype(jnp.float32) + 1.0)
    raise ValueError(rule)


def update_state_projector(
    state: OnlineState,
    p: jax.Array,
    *,
    discount: str,
    num_steps: int,
) -> OnlineState:
    """Fold one (d, d) projector-like matrix into the running average
    (jittable). The shared tail of :func:`update_state` — the
    merge-interval steady state (``cfg.merge_interval > 1``) folds the
    MEAN of the worker projectors here on the steps between merges,
    with the same discount weights as the merged-projector fold."""
    step = state.step + 1
    w = _discount(discount, step, num_steps)
    p = p.astype(state.sigma_tilde.dtype)
    if discount == "1/t":
        sigma = state.sigma_tilde * (1.0 - w) + p * w
    else:
        sigma = state.sigma_tilde + p * w
    # the f32 discount scalar promotes a non-f32 state (state_dtype =
    # bfloat16) to f32 — cast back so the state dtype is stable (a scan
    # carry REQUIRES it; the per-step loop would otherwise promote
    # silently on the first fold)
    return OnlineState(
        sigma_tilde=sigma.astype(state.sigma_tilde.dtype), step=step
    )


def update_state(
    state: OnlineState,
    v_bar: jax.Array,
    *,
    discount: str,
    num_steps: int,
) -> OnlineState:
    """Fold one merged eigenspace into the online running average (jittable)."""
    return update_state_projector(
        state, projector(v_bar), discount=discount, num_steps=num_steps
    )


def online_distributed_pca(
    stream: Iterable[jax.Array],
    cfg: PCAConfig,
    *,
    pool: WorkerPool | None = None,
    state: OnlineState | None = None,
    on_step: Callable[[int, OnlineState, jax.Array], None] | None = None,
    worker_masks: Iterator[jax.Array] | None = None,
    max_steps: int | None | str = "auto",
    step_hook: Callable | None = None,
    ingest_stats=None,
):
    """Run the full online algorithm over a stream of ``(m, n, d)`` blocks.

    Args:
      stream: iterable yielding per-step worker blocks, shape
        ``(num_workers, rows_per_worker, dim)``. The stream *advances* —
        each step consumes fresh data (fixes B6).
      cfg: algorithm configuration. ``cfg.num_steps`` caps the loop; a
        shorter stream ends it early (true online behavior).
      pool: optional pre-built WorkerPool (else built from cfg).
      state: optional resume state (checkpoint restart, SURVEY.md §5.4).
      on_step: optional callback ``(t, state, v_bar)`` after each fold —
        metrics/checkpoint hook.
      worker_masks: optional iterable of ``(m,)`` {0,1} masks for fault
        injection (SURVEY.md §5.3) — one per step; arrays/sequences are
        accepted (wrapped with ``iter`` here, ONE place, so every
        caller's contract matches).
      max_steps: ``"auto"`` caps the *total* step count (including resumed
        state) at ``cfg.num_steps`` — except under ``discount="1/t"``,
        where the auto cap is open-ended (a running mean only improves by
        folding more rounds); ``None`` consumes the whole stream
        (``partial_fit`` semantics — fold extra rounds past T); an int is
        an explicit total cap, honored under every discount rule.
      step_hook: optional ``(step_fn, state, x_blocks, t) -> (state,
        v_bar)`` wrapper around each step execution — the supervisor's
        retry/backoff hook point (``runtime/supervisor.py``): it may
        re-invoke ``step_fn`` on transient failures or escalate. ``None``
        calls the step directly (zero overhead on the unsupervised path).
      ingest_stats: optional ``runtime.prefetch.PrefetchStats`` — the
        prefetch pipeline counts its queue stalls/occupancy into it, so
        ingest-bound vs compute-bound is readable from the run report
        (attach the same object to a ``MetricsLogger`` via
        ``attach_ingest``). Ignored when ``cfg.prefetch_depth == 0``.

    Returns:
      ``(w, state)`` — ``w`` the final (dim, k) principal subspace estimate
      (descending order, canonical signs), ``state`` the final online state.
    """
    if worker_masks is not None:
        worker_masks = iter(worker_masks)  # arrays/lists -> per-step iter
    if cfg.backend == "feature_sharded":
        if pool is not None:
            raise ValueError(
                "backend='feature_sharded' builds its own 2-D mesh step — "
                "an explicit WorkerPool cannot drive it (drop the pool "
                "argument, or use backend='shard_map' with your pool)"
            )
        return _fit_feature_sharded(
            stream, cfg, state=state, on_step=on_step,
            worker_masks=worker_masks, max_steps=max_steps,
            step_hook=step_hook, ingest_stats=ingest_stats,
        )
    if pool is None:
        pool = WorkerPool(
            cfg.num_workers,
            backend="local" if cfg.backend == "auto" and len(jax.devices()) == 1
            else ("shard_map" if cfg.backend == "auto" else cfg.backend),
            solver=cfg.resolved_local_solver(),
            subspace_iters=cfg.subspace_iters,
            orth_method=cfg.orth_method,
            compute_dtype=cfg.compute_dtype,
        )
    if state is None:
        state = OnlineState.initial(cfg.dim, cfg.state_dtype)

    update = jax.jit(
        lambda s, v: update_state(
            s, v, discount=cfg.discount, num_steps=cfg.num_steps
        )
    )
    update_p = jax.jit(
        lambda s, p: update_state_projector(
            s, p, discount=cfg.discount, num_steps=cfg.num_steps
        )
    )

    # online warm start (cfg.warm_start_iters): after the cold first round,
    # warm-start each worker's subspace iteration from the previous merged
    # estimate at the short iteration count — the same lever the scan
    # trainer has, threaded through the loop instead of a scan carry
    warm_iters = cfg.resolved_warm_start()
    warm = warm_iters is not None
    v_prev = None
    # merge-interval steady state (cfg.merge_interval = s): the merged
    # eigensolve runs on steps t with (t-1) % s == 0; the steps between
    # fold the masked mean of worker projectors (pool.round's sigma_bar)
    # at the same discount weight, and the warm carry keeps the last
    # merged basis. The phase counter is HOST state committed only on a
    # step's successful return, so a supervisor step_hook retry
    # (runtime/supervisor.py) re-runs the SAME phase instead of drifting.
    s_int = cfg.merge_interval
    done_cell = [int(state.step)]

    def step(st, x_blocks):
        nonlocal v_prev
        t = done_cell[0] + 1
        merge_now = s_int == 1 or (t - 1) % s_int == 0
        mask = next(worker_masks) if worker_masks is not None else None
        # pool.shard is idempotent, so prefetch-placed blocks pass through
        sigma_bar, v_bar = pool.round(
            pool.shard(x_blocks), cfg.k, worker_mask=mask,
            v0=v_prev,
            iters=warm_iters if v_prev is not None else None,
            orth=(
                cfg.resolved_warm_orth() if v_prev is not None else None
            ),
            merge=merge_now,
        )
        if merge_now:
            if warm:
                # an ALL-masked round merges to zeros; warm-starting from
                # a zero basis is a fixed point of the solver (orth(0) =
                # 0), so the carry keeps the last LIVE basis — and until
                # any round survives, v_prev stays None and rounds run
                # cold (round-5 §5.3 fix: an all-masked FIRST round
                # previously dead-ended the whole fit at a zero
                # estimate). Liveness is read from the MASK on the host
                # (v_bar is all-zero exactly when the mask is all-zero)
                # — checking v_bar itself would fetch device values
                # every masked round and serialize the prefetch pipeline.
                if mask is None or bool(np.any(np.asarray(mask))):
                    v_prev = v_bar
            st, out = update(st, v_bar), v_bar
        else:
            # between merges: fold the (masked — the drop takes effect
            # THIS round, §5.3) mean projector; the on_step value is the
            # carried last-merged basis (zeros before any live merge)
            st = update_p(st, sigma_bar)
            out = (
                v_prev if v_prev is not None
                else jnp.zeros((cfg.dim, cfg.k), jnp.float32)
            )
        done_cell[0] = t
        return st, out

    state = _drive_stream(
        stream, cfg, place=pool.shard, step=step, state=state,
        on_step=on_step, max_steps=max_steps, step_hook=step_hook,
        ingest_stats=ingest_stats,
    )
    w = top_k_eigvecs(state.sigma_tilde, cfg.k)
    return w, state


def _drive_stream(stream, cfg, *, place, step, state, on_step, max_steps,
                  step_hook=None, ingest_stats=None):
    """Shared training-loop scaffolding for the per-step backends: prefetch
    wiring, the step cap (open-ended for 1/t running means), step
    bookkeeping, and deterministic prefetch-producer cleanup.

    ``step(state, x) -> (state, v_bar)``; ``place`` stages a host block on
    the backend's devices (must be idempotent — the prefetch producer
    applies it ahead of the loop). ``step_hook`` (see
    :func:`online_distributed_pca`) wraps each step execution — the
    supervisor's retry hook.
    """
    if cfg.prefetch_depth > 0:
        # overlap host block prep + host->HBM transfer with device compute
        # (the reference's 5-in-flight AMQP window, done as a real pipeline).
        # NOTE: the producer reads ahead, so the caller's underlying
        # iterable may be advanced past the last consumed step — pass
        # prefetch_depth=0 when sharing an iterator across fit calls.
        from distributed_eigenspaces_tpu.runtime.prefetch import (
            prefetch_stream,
        )

        stream = prefetch_stream(
            stream, depth=cfg.prefetch_depth, place=place,
            stats=ingest_stats,
        )

    # function-level import: utils.__init__ pulls checkpoint, which imports
    # this module — a top-level import would cycle
    from distributed_eigenspaces_tpu.utils.tracing import annotate_step

    cap = cfg.num_steps if max_steps == "auto" else max_steps
    # the "auto" cap is open-ended for a 1/t running mean (folding extra
    # rounds only improves the estimate); an EXPLICIT integer cap is a
    # contract and is honored under every discount rule
    open_ended = max_steps == "auto" and cfg.discount == "1/t"
    steps_done = int(state.step)
    try:
        for x_blocks in stream:
            if cap is not None and steps_done >= cap and not open_ended:
                break
            with annotate_step(steps_done + 1):
                if step_hook is None:
                    state, v_bar = step(state, x_blocks)
                else:
                    state, v_bar = step_hook(
                        step, state, x_blocks, steps_done + 1
                    )
            steps_done += 1
            if on_step is not None:
                on_step(steps_done, state, v_bar)
    finally:
        # deterministic cleanup of the prefetch producer thread (and its
        # pinned device blocks) when the loop exits early
        close = getattr(stream, "close", None)
        if close is not None:
            close()
    return state


def _fit_feature_sharded(
    stream,
    cfg: PCAConfig,
    *,
    state=None,
    on_step=None,
    worker_masks=None,
    max_steps="auto",
    step_hook=None,
    ingest_stats=None,
):
    """The large-d backend behind :func:`online_distributed_pca`: routes the
    same stream/loop semantics through the feature-sharded training step
    (``parallel/feature_sharded.py`` — d sharded over a second mesh axis,
    no d x d matrix anywhere, rank-r online state).
    """
    from distributed_eigenspaces_tpu.ops.linalg import canonicalize_signs
    from distributed_eigenspaces_tpu.parallel.feature_sharded import (
        auto_feature_mesh,
        make_feature_sharded_step,
    )

    mesh = auto_feature_mesh(cfg)
    fstep = make_feature_sharded_step(
        cfg, mesh, seed=cfg.seed, collectives=cfg.collectives
    )
    if state is None:
        state = fstep.init_state()

    place = lambda x: jax.device_put(  # noqa: E731
        jnp.asarray(x), fstep.x_sharding
    )

    def step(st, x):
        # masked survivor merge on the 2-D mesh: the same §5.3 fault
        # mechanism the DP backends have (weighted exclusion of failed
        # workers), on the path where failures matter most
        mask = next(worker_masks) if worker_masks is not None else None
        return fstep(st, place(x), worker_mask=mask)

    state = _drive_stream(
        stream, cfg, place=place, step=step,
        state=state, on_step=on_step, max_steps=max_steps,
        step_hook=step_hook, ingest_stats=ingest_stats,
    )
    w = canonicalize_signs(state.u[:, : cfg.k])
    return w, state


def one_shot_round(
    x_blocks: jax.Array,
    k: int,
    *,
    pool: WorkerPool | None = None,
    backend: str = "auto",
):
    """Single distributed round — parity with ``python distributed.py``.

    The reference AMQP path runs exactly one merge round and then drops the
    result on the floor (``distributed.py:126-131``, B4). This returns both
    the merged projector average ``sigma_bar`` (what the master computed) and
    its top-k eigenspace (what it should have produced).
    """
    m = x_blocks.shape[0]
    if pool is None:
        pool = WorkerPool(m, backend=backend)
    sigma_bar, v_bar = pool.round(pool.shard(x_blocks), k)
    return sigma_bar, v_bar
