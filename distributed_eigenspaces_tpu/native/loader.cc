// Native host-side data runtime for distributed_eigenspaces_tpu.
//
// The reference's data path is pure Python: pickle loading
// (load_data.py:8-15), numpy grayscale + flatten (distributed.py:170-173).
// On a TPU host the input pipeline must keep one chip fed at HBM-copy rate,
// so the conversion inner loops and the read-ahead live here:
//
//   - u8_nhwc_to_gray_f32 / u8_to_f32: multithreaded uint8 -> float32
//     conversion (channel-mean grayscale or plain widen), the hot loop of
//     CIFAR-style ingestion (reference C5).
//   - f32_absmax / f32_quantize_i8: the symmetric int8 wire-format prep
//     (data/bin_stream.py::quantize_file_i8) — vectorization-shaped inner
//     loops (bit-mask abs, unsigned-compare max) + threading.
//   - reader_*: a chunked file reader with one background read-ahead thread
//     (double buffer), so disk latency overlaps host->device transfer.
//
// Built with plain g++ (no external deps); loaded via ctypes
// (runtime/native.py) with a numpy fallback when unavailable.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---- conversion kernels ---------------------------------------------------

// (n, h, w, c) uint8 -> (n, h*w) float32 channel-mean grayscale.
void u8_nhwc_to_gray_f32(const uint8_t* in, float* out, int64_t n,
                         int64_t h, int64_t w, int64_t c,
                         int32_t num_threads) {
  const int64_t hw = h * w;
  const float inv_c = 1.0f / static_cast<float>(c);
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      const uint8_t* row = in + i * hw * c;
      float* dst = out + i * hw;
      for (int64_t p = 0; p < hw; ++p) {
        int32_t acc = 0;
        for (int64_t ch = 0; ch < c; ++ch) acc += row[p * c + ch];
        dst[p] = static_cast<float>(acc) * inv_c;
      }
    }
  };
  if (num_threads <= 1 || n < num_threads) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (n + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(n, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// flat uint8 -> float32 widen (the RGB 3072-d path, B7).
void u8_to_f32(const uint8_t* in, float* out, int64_t count,
               int32_t num_threads) {
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) out[i] = static_cast<float>(in[i]);
  };
  if (num_threads <= 1 || count < (1 << 20)) {
    worker(0, count);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (count + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(count, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// ---- int8 quantization kernels -------------------------------------------
//
// Prep path of the out-of-core int8 wire format (data/bin_stream.py): a
// symmetric global scale cancels in eigenvectors, so quantization is the
// only host-side transform a 400M-row fp32 corpus needs before streaming.
// Two passes, both threaded: absmax (the scale), then scale+round+clip.

// branch-free 8-wide unrolled reduction: a single `if (a > m)` chain is a
// serial dependency the compiler cannot vectorize; independent lanes
// become packed max instructions (measured 4x vs the naive loop on one
// core — the bar is numpy's SIMD absmax, which the naive loop LOSES to)
static float absmax_range(const float* in, int64_t lo, int64_t hi) {
  // abs = clear the sign bit; max as unsigned int compare — valid because
  // non-negative IEEE floats order identically to their bit patterns.
  // Both ops are single packed integer instructions, so the 8 lanes
  // vectorize where float max (NaN semantics) and branchy abs do not.
  uint32_t m[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  const uint32_t* bits = reinterpret_cast<const uint32_t*>(in);
  int64_t i = lo;
  for (; i + 8 <= hi; i += 8) {
    for (int64_t l = 0; l < 8; ++l) {
      uint32_t a = bits[i + l] & 0x7fffffffu;
      m[l] = m[l] > a ? m[l] : a;
    }
  }
  for (; i < hi; ++i) {
    uint32_t a = bits[i] & 0x7fffffffu;
    m[0] = m[0] > a ? m[0] : a;
  }
  uint32_t r = 0;
  for (int64_t l = 0; l < 8; ++l) r = r > m[l] ? r : m[l];
  float out;
  memcpy(&out, &r, sizeof(out));
  return out;
}

float f32_absmax(const float* in, int64_t count, int32_t num_threads) {
  if (num_threads <= 1 || count < (1 << 20)) {
    return absmax_range(in, 0, count);
  }
  std::vector<float> part(static_cast<size_t>(num_threads), 0.0f);
  std::vector<std::thread> ts;
  int64_t per = (count + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(count, lo + per);
    if (lo >= hi) break;
    ts.emplace_back([&part, in, t, lo, hi] {
      part[static_cast<size_t>(t)] = absmax_range(in, lo, hi);
    });
  }
  for (auto& t : ts) t.join();
  float m = 0.0f;
  for (float p : part) {
    if (p > m) m = p;
  }
  return m;
}

// out[i] = clip(round(in[i] * scale), -127, 127); round half away from zero
// (matches numpy's np.round to within the symmetric-quantization noise the
// accuracy gate already charges — exact np.round parity is banker's
// rounding, which differs only at exact .5 multiples of 1/scale).
void f32_quantize_i8(const float* in, int8_t* out, int64_t count,
                     float scale, int32_t num_threads) {
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      float v = in[i] * scale;
      v = v < 0 ? v - 0.5f : v + 0.5f;
      // clamp BEFORE the int cast: float->int32 of a value outside
      // int32's range is UB (measured: 3e9f casts to INT_MIN under -O3,
      // sign-flipping the clipped result). The float clamp also absorbs
      // +/-inf; NaN (both comparisons false) maps to 0 explicitly.
      if (v > 127.0f) v = 127.0f;
      if (v < -127.0f) v = -127.0f;
      out[i] = static_cast<int8_t>(v == v ? static_cast<int32_t>(v) : 0);
    }
  };
  if (num_threads <= 1 || count < (1 << 20)) {
    worker(0, count);
    return;
  }
  std::vector<std::thread> ts;
  int64_t per = (count + num_threads - 1) / num_threads;
  for (int32_t t = 0; t < num_threads; ++t) {
    int64_t lo = t * per, hi = std::min<int64_t>(count, lo + per);
    if (lo >= hi) break;
    ts.emplace_back(worker, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// ---- double-buffered chunk reader ----------------------------------------

struct Reader {
  FILE* f = nullptr;
  int64_t chunk = 0;
  int64_t skip = 0;             // bytes to skip after each chunk (stride)
  std::vector<uint8_t> ahead;   // read-ahead buffer
  int64_t ahead_len = 0;        // bytes valid in `ahead`
  bool ahead_ready = false;
  bool eof = false;
  bool stop = false;
  std::thread th;
  std::mutex mu;
  std::condition_variable cv;

  void loop() {
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      cv.wait(lk, [&] { return stop || !ahead_ready; });
      if (stop) return;
      lk.unlock();
      int64_t got = static_cast<int64_t>(
          fread(ahead.data(), 1, static_cast<size_t>(chunk), f));
      bool hit_eof = got < chunk;
      if (!hit_eof && skip > 0 && fseeko(f, skip, SEEK_CUR) != 0) {
        // NOTE: on regular files fseeko past EOF SUCCEEDS (POSIX), so a
        // stride overrun terminates via the next fread returning 0, not
        // here — this branch only fires for non-seekable streams
        hit_eof = true;
      }
      lk.lock();
      ahead_len = got;
      ahead_ready = true;
      if (hit_eof) eof = true;
      cv.notify_all();
      if (eof) return;
    }
  }
};

// ``offset``: initial seek; ``skip``: bytes skipped after EVERY chunk —
// the strided access a multi-host reader needs when each host owns a
// contiguous row slice of every step in one shared file.
void* reader_open_strided(const char* path, int64_t chunk_bytes,
                          int64_t offset, int64_t skip) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (offset > 0 && fseeko(f, offset, SEEK_SET) != 0) {
    fclose(f);
    return nullptr;
  }
  Reader* r = new Reader();
  r->f = f;
  r->chunk = chunk_bytes;
  r->skip = skip;
  r->ahead.resize(static_cast<size_t>(chunk_bytes));
  r->th = std::thread([r] { r->loop(); });
  return r;
}

void* reader_open(const char* path, int64_t chunk_bytes) {
  return reader_open_strided(path, chunk_bytes, 0, 0);
}

// Copy the next chunk into buf; returns bytes delivered (0 at EOF).
int64_t reader_next(void* h, uint8_t* buf) {
  Reader* r = static_cast<Reader*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  // wait for data OR a finished reader (eof with its final chunk already
  // consumed must return 0 immediately, not wait on a dead thread)
  r->cv.wait(lk, [&] { return r->ahead_ready || r->eof; });
  if (!r->ahead_ready) return 0;  // eof, final chunk already delivered
  int64_t got = r->ahead_len;
  if (got > 0) memcpy(buf, r->ahead.data(), static_cast<size_t>(got));
  r->ahead_ready = false;
  r->cv.notify_all();
  return got;
}

void reader_close(void* h) {
  Reader* r = static_cast<Reader*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->stop = true;
  }
  r->cv.notify_all();
  if (r->th.joinable()) r->th.join();
  fclose(r->f);
  delete r;
}

}  // extern "C"
