"""Out-of-core block streaming from raw binary row files.

BASELINE.md config 5 (CLIP ViT-L embeddings, 768-d, ~400M rows ≈ 1.2 TB
fp32) cannot follow the reference's data model — every process loads the
FULL dataset into memory (``distributed.py:169``). This module streams
``(m, n, d)`` worker blocks straight from disk through the native
double-buffered :class:`..runtime.native.ChunkReader` (C++ read-ahead
thread overlapping disk latency with host->device transfer), so host
memory holds only ~2 in-flight steps regardless of dataset size.

File format: flat rows, ``dtype`` (float32 / bfloat16 / uint8 / int8), row
length ``dim`` — i.e. exactly ``array.tobytes()`` of an ``(N, dim)`` matrix.
``write_rows`` produces it; uint8 rows are widened to float32 by the native
conversion kernel, bfloat16 rows are bit-extended (uint16 -> high half of a
float32 word — a reinterpretation, not a value cast) on the way in.

Quantized wire format: with an integer ``out_dtype`` (e.g. ``jnp.int8``
over an int8 file), blocks pass through UNCONVERTED — 4x fewer bytes cross
host->device than fp32, and the widening happens on-device as part of the
compute-dtype cast. For symmetric (zero-offset) int8 quantization the
global scale cancels in eigenvectors, so the PCA subspace needs no
dequantization at all; see ``evals.py`` config 5.
"""

from __future__ import annotations

import os
from typing import Iterator

import jax.numpy as jnp
import numpy as np

from distributed_eigenspaces_tpu.runtime.native import ChunkReader, to_f32


def write_rows(path: str, data: np.ndarray) -> None:
    """Write ``(N, d)`` rows as the flat binary format (fixtures / prep)."""
    np.ascontiguousarray(data).tofile(path)


def quantize_file_i8(
    src: str,
    dst: str,
    *,
    dim: int,
    chunk_rows: int = 65536,
    scale: float | None = None,
) -> tuple[float, int]:
    """Quantize a flat float32 row file into the int8 wire format, out of
    core: two streaming passes through the double-buffered native reader
    (pass 1 global absmax unless ``scale`` is given; pass 2 quantize +
    write), O(chunk) host memory — the prep tool for the 400M-row config
    (BASELINE.md config 5; the reference has no counterpart because its
    data model is everything-in-RAM, ``distributed.py:169``).

    Returns ``(scale, rows)``. The symmetric global scale cancels in
    eigenvectors, so consumers (``bin_block_stream(out_dtype=jnp.int8)``)
    never dequantize; record it only if reconstructed VALUES are needed.
    """
    from distributed_eigenspaces_tpu.runtime.native import (
        absmax_f32,
        quantize_i8,
    )

    total = num_rows(src, dim, np.float32)
    chunk_bytes = chunk_rows * dim * 4
    if scale is None:
        m = 0.0
        with ChunkReader(src, chunk_bytes) as rd:
            for chunk in rd:
                m = max(m, absmax_f32(np.frombuffer(chunk, np.float32)))
        scale = 127.0 / max(m, 1e-30)

    with ChunkReader(src, chunk_bytes) as rd, open(dst, "wb") as f:
        for chunk in rd:
            f.write(
                quantize_i8(
                    np.frombuffer(chunk, np.float32), scale
                ).tobytes()
            )
    return float(scale), total


def num_rows(path: str, dim: int, dtype=np.float32) -> int:
    itemsize = np.dtype(dtype).itemsize
    size = os.path.getsize(path)
    if size % (dim * itemsize):
        raise ValueError(
            f"{path}: {size} bytes is not a whole number of "
            f"{dim}x{np.dtype(dtype).name} rows"
        )
    return size // (dim * itemsize)


def window_stream(blocks, window: int):
    """Stack a block iterator into ``(S, m, n, d)`` windows of up to
    ``window`` steps (the last may be ragged) — the staging unit of the
    out-of-core segmented whole-fit (``make_segmented_fit(...).fit_windows``):
    one window = one S-step device program, so the per-step dispatch cost
    of the tunnelled per-step trainer drops to 1/S per step.

    Works on device blocks (``jnp.stack`` runs on device) or host arrays
    — host (numpy) blocks stack with ``np.stack`` and STAY host-resident,
    so the consumer (or a ``prefetch_stream`` ``place``) controls the one
    host->device transfer and its sharding; a ``jnp.stack`` here would
    silently commit every window to the default device first.
    """
    if window < 1:
        raise ValueError(f"window must be >= 1, got {window}")

    def stack(bs):
        if all(isinstance(b, np.ndarray) for b in bs):
            return np.stack(bs)
        return jnp.stack(bs)

    buf = []
    for b in blocks:
        buf.append(b)
        if len(buf) == window:
            yield stack(buf)
            buf = []
    if buf:
        yield stack(buf)


def bin_block_stream(
    path: str,
    *,
    dim: int,
    num_workers: int,
    rows_per_worker: int,
    num_steps: int | None = None,
    dtype=np.float32,
    out_dtype=jnp.float32,
    remainder: str = "drop",
    worker_range: tuple[int, int] | None = None,
    start_row: int = 0,
) -> Iterator[jnp.ndarray]:
    """Yield ``(num_workers, rows_per_worker, dim)`` blocks from a binary
    row file without ever materializing the dataset.

    Same contract as :func:`.stream.block_stream` (advancing cursor,
    explicit remainder policy) but O(step) memory: one step's bytes are
    read per chunk, with the next chunk prefetched by the native reader's
    background thread.

    ``worker_range=(lo, hi)``: multi-host mode — yield only workers
    ``[lo, hi)`` of each ``num_workers``-worker step, shape
    ``(hi - lo, rows_per_worker, dim)``. The strided reader seeks past
    the other hosts' rows, so each host reads ONLY the bytes of the
    workers it owns from one shared file (the out-of-core twin of
    ``multihost.host_worker_range`` — contrast the reference, where every
    process reads the full dataset, ``distributed.py:169``). A ragged
    final step is dropped (only ``remainder="drop"`` is meaningful: a
    partial step may cut mid-stride, so other policies are rejected).

    ``start_row`` seeks past already-consumed rows before the first read
    — the resume argument for the cursor ``utils.checkpoint`` saves
    (``steps_done * num_workers * rows_per_worker``). It must land on a
    step boundary: the file's step layout is fixed, so a mid-step seek
    would silently re-split every block (and in strided mode would
    misalign every host's worker slots).
    """
    if remainder not in ("drop", "pad", "error"):
        raise ValueError(f"unknown remainder policy: {remainder!r}")
    in_dt = np.dtype(dtype)
    is_bf16 = in_dt.name == "bfloat16"
    out_is_int = jnp.issubdtype(jnp.dtype(out_dtype), jnp.integer)
    if out_is_int and (is_bf16 or in_dt != np.dtype(out_dtype)):
        raise ValueError(
            f"integer out_dtype={jnp.dtype(out_dtype).name} requires the "
            f"same on-disk dtype (got {in_dt.name}) — the passthrough "
            "path ships the stored bytes to the device unconverted"
        )
    host_dt = in_dt if out_is_int else np.float32
    step_rows = num_workers * rows_per_worker
    total = num_rows(path, dim, dtype)
    if step_rows > total:
        raise ValueError(f"one step needs {step_rows} rows, file has {total}")

    row_bytes = dim * in_dt.itemsize
    if start_row:
        if start_row % step_rows:
            raise ValueError(
                f"start_row={start_row} is not a step boundary "
                f"(step_rows={step_rows}) — checkpoint cursors are "
                "whole-step row offsets"
            )
        if start_row > total:
            raise ValueError(
                f"start_row={start_row} beyond the file's {total} rows"
            )
    skipped_steps = start_row // step_rows
    offset = start_row * row_bytes
    skip = 0
    out_workers = num_workers
    if worker_range is not None:
        lo, hi = worker_range
        if not (0 <= lo < hi <= num_workers):
            raise ValueError(
                f"worker_range {worker_range} invalid: need "
                f"0 <= lo < hi <= num_workers (= {num_workers})"
            )
        if remainder != "drop":
            raise ValueError(
                "worker_range supports remainder='drop' only (a partial "
                "final step may cut mid-stride)"
            )
        out_workers = hi - lo
        # seek past the other hosts' leading worker slots AND any resumed
        # whole steps (start_row is whole-step, so the strided layout
        # stays aligned across hosts)
        offset = (
            lo * rows_per_worker + skipped_steps * step_rows
        ) * row_bytes
        skip = (num_workers - out_workers) * rows_per_worker * row_bytes
        # every host must agree on the step count: a ragged final step may
        # be complete for low worker ranges but missing for high ones, so
        # cap at the number of FULL steps left after the seek
        full_steps = total // step_rows - skipped_steps
        num_steps = (
            full_steps if num_steps is None else min(num_steps, full_steps)
        )
    chunk_bytes = out_workers * rows_per_worker * row_bytes
    num_workers = out_workers

    def convert(buf: bytes) -> np.ndarray:
        if is_bf16:
            # bit-reinterpret: each bf16 word is the high half of an f32
            bits = np.frombuffer(buf, dtype=np.uint16)
            return (bits.astype(np.uint32) << 16).view(np.float32)
        arr = np.frombuffer(buf, dtype=in_dt)
        if out_is_int:
            return arr  # quantized passthrough: device widens during compute
        if in_dt == np.uint8:
            arr = to_f32(arr)  # native widen kernel
        return np.asarray(arr, np.float32)

    steps = 0
    with ChunkReader(path, chunk_bytes, offset=offset, skip=skip) as reader:
        it = iter(reader)
        while True:
            # check the cap BEFORE pulling: past it, a chunk would be read
            # only to be discarded (and in strided mode the per-host
            # "reads ONLY its own bytes" contract would leak one chunk)
            if num_steps is not None and steps >= num_steps:
                return
            chunk = next(it, None)
            if chunk is None:
                return
            if len(chunk) < chunk_bytes:  # ragged tail
                tail_rows = len(chunk) // (dim * in_dt.itemsize)
                if tail_rows == 0 or remainder == "drop":
                    return
                if remainder == "error":
                    raise ValueError(
                        f"{tail_rows} remainder rows (step={step_rows}); "
                        "set remainder='drop'/'pad' or adjust sizes"
                    )
                block = np.zeros((step_rows, dim), host_dt)
                block[:tail_rows] = convert(
                    chunk[: tail_rows * dim * in_dt.itemsize]
                ).reshape(tail_rows, dim)
                yield jnp.asarray(
                    block.reshape(num_workers, rows_per_worker, dim),
                    dtype=out_dtype,
                )
                return
            steps += 1
            yield jnp.asarray(
                convert(chunk).reshape(num_workers, rows_per_worker, dim),
                dtype=out_dtype,
            )


def main(argv=None) -> int:
    """``det-pca-quantize``: the out-of-core int8 prep tool as a command —
    quantize a flat float32 row file into the wire format the streaming
    trainers consume (``python -m distributed_eigenspaces_tpu.data.bin_stream
    src.f32 dst.i8 --dim 768``)."""
    import argparse
    import json

    p = argparse.ArgumentParser(
        description="Quantize a flat float32 row file to the int8 wire "
        "format (symmetric global scale; two streaming passes, O(chunk) "
        "memory)"
    )
    p.add_argument("src", help="flat float32 row file ((N, dim).tobytes())")
    p.add_argument("dst", help="output int8 file")
    p.add_argument("--dim", type=int, required=True)
    p.add_argument("--chunk-rows", type=int, default=65536)
    p.add_argument("--scale", type=float, default=None,
                   help="explicit scale (skips the absmax pass)")
    args = p.parse_args(argv)
    scale, rows = quantize_file_i8(
        args.src, args.dst, dim=args.dim, chunk_rows=args.chunk_rows,
        scale=args.scale,
    )
    print(json.dumps({
        "rows": rows, "dim": args.dim, "scale": scale,
        "wire_bytes": rows * args.dim,
        "float_bytes": rows * args.dim * 4,
    }))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
