"""Streaming batcher: host rows -> per-step (m, n, d) worker blocks.

Replaces both reference batchers (C6, SURVEY.md §2): the notebook's
``make_batches`` (cell 8, ragged tail kept) and the CLI's contiguous split
that silently drops the remainder (``distributed.py:99-104``). The remainder
policy here is explicit, and the stream **advances** its cursor every step —
the notebook re-read the same first m batches forever (B6).
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np


def make_batches(n_rows: int, batch_size: int, *, keep_tail: bool = True):
    """Contiguous index ranges [(lo, hi), ...] — reference cell 8 semantics
    (``keep_tail=True``) or the CLI's drop behavior (``False``)."""
    ranges = [
        (lo, min(lo + batch_size, n_rows))
        for lo in range(0, n_rows, batch_size)
    ]
    if not keep_tail and ranges and ranges[-1][1] - ranges[-1][0] < batch_size:
        ranges.pop()
    return ranges


def _place(block: np.ndarray, dtype, device: bool):
    if device:
        return jnp.asarray(block, dtype=dtype)
    return np.asarray(block, dtype=jnp.dtype(dtype))


def quantize_block_i8(block) -> np.ndarray:
    """Symmetric global int8 quantization of one staged block (host side).

    The scale (absmax/127) is NOT returned: a symmetric scale cancels in
    eigenvectors (the contract the int8 wire format already relies on,
    ``data/bin_stream.py``), so PCA consumers never dequantize. One scale
    per block — every worker inside a block shares it, and per-block
    scales cancel per-worker-solve anyway (the merge consumes only the
    orthonormal factors). Used by the whole-fit staging paths when
    ``PCAConfig.stage_dtype == "int8"``: the solvers contract int8
    natively (exact int32 Gram; in-loop-widened streaming passes reading
    half the bf16 bytes — the HBM-bound warm step's round-5 win).
    """
    b = np.asarray(block, np.float32)
    amax = float(np.max(np.abs(b))) if b.size else 0.0
    if not np.isfinite(amax):
        # loud beats silent: an inf makes the scale zero (whole block
        # quantizes to zeros and is folded as if real), a NaN makes the
        # int8 cast undefined garbage — and host-side quantization runs
        # BEFORE the on-device DET_CHECKIFY NaN guards could trip
        raise ValueError(
            "quantize_block_i8: block contains non-finite values"
        )
    if amax == 0.0:
        return np.zeros(b.shape, np.int8)
    return np.clip(np.round(b * (127.0 / amax)), -127, 127).astype(np.int8)


def quantize_block_i8_device(block):
    """Device-side twin of :func:`quantize_block_i8` (same math: global
    symmetric absmax scale, round-half-even, clip, int8) for blocks that
    are ALREADY device-resident — quantizing on device instead of
    pulling fp32 to host saves the full block transfer on exactly the
    slow-link setups the staging exists to help. Equality with the host
    version is pinned in tests/test_int8_stage.py — including the
    non-finite contract: the SCALAR absmax (4 bytes, already reduced on
    device) is fetched and checked on host, so a NaN/inf block raises
    here exactly like the host twin instead of being laundered into
    finite int8 garbage by the cast (no downstream guard could ever see
    it — the int8 block is all-finite)."""
    b = block.astype(jnp.float32)
    amax = float(jnp.max(jnp.abs(b)))  # scalar fetch: the loud guard
    if not np.isfinite(amax):
        raise ValueError(
            "quantize_block_i8_device: block contains non-finite values"
        )
    if amax == 0.0:
        return jnp.zeros(block.shape, jnp.int8)
    return jnp.clip(
        jnp.round(b * (127.0 / amax)), -127, 127
    ).astype(jnp.int8)


def stage_blocks(blocks, stage):
    """Stage an iterable of ``(m, n, d)`` blocks in ``stage`` dtype — THE
    one definition of the staging contract (estimator whole fits, the
    sketch online continuation, and bench.py all route through it so
    their staging cannot drift): int8 quantizes via
    :func:`quantize_block_i8`; float dtypes cast (no-copy when the block
    already matches)."""
    stage = jnp.dtype(stage)
    if stage == jnp.dtype(jnp.int8):
        # device-resident blocks quantize ON device (pulling fp32 to
        # host just to quantize would drag the full block over the
        # link); host blocks take the host quantizer — same math and
        # same loud non-finite contract, pinned equal by test
        return (
            quantize_block_i8_device(b) if isinstance(b, jax.Array)
            else quantize_block_i8(np.asarray(b))
            for b in blocks
        )
    # host-side cast for EVERY input (numpy stays numpy, device arrays
    # come back to host): the consumers (window_stream + the trainers'
    # sharded device_put) own placement — a jnp cast here would commit
    # blocks to the default device and break the per-device staging
    # budget on multi-device meshes
    return (np.asarray(b, stage) for b in blocks)


def block_stream(
    data,
    *,
    num_workers: int,
    rows_per_worker: int,
    num_steps: int | None = None,
    remainder: str = "drop",
    dtype=jnp.float32,
    wrap: bool = False,
    device: bool = True,
    start_row: int = 0,
) -> Iterator[jax.Array]:
    """Yield (num_workers, rows_per_worker, d) blocks from (N, d) host data.

    Each step consumes ``num_workers * rows_per_worker`` fresh rows (advancing
    cursor — the B6 fix). Remainder policy for the final partial step:
    ``"drop"`` (reference behavior), ``"pad"`` (zero rows; the Gram kernel
    normalizes by the *unpadded* count upstream, so pad only when callers
    handle weighting), or ``"error"``. ``wrap=True`` restarts from row 0
    instead of stopping (infinite epochs for throughput benchmarking).
    ``device=False`` yields HOST numpy arrays instead of placing each
    block on a device — for consumers that stage themselves (the
    whole-fit trainers), where a per-block device round trip would both
    waste host<->device bandwidth and pile up transient HBM buffers.
    ``start_row`` seeks the cursor before the first step — the resume
    argument for the row offset ``utils.checkpoint`` saves (a checkpoint
    cursor is ``steps_done * step_rows``), so a restarted run continues
    on unseen rows instead of replaying the stream from row 0.
    """
    data = np.asarray(data)
    n_total, d = data.shape
    step_rows = num_workers * rows_per_worker
    if step_rows > n_total:
        raise ValueError(
            f"one step needs {step_rows} rows, dataset has {n_total}"
        )
    if not 0 <= start_row <= n_total:
        raise ValueError(
            f"start_row={start_row} outside the dataset's {n_total} rows"
        )
    cursor, steps = start_row, 0
    while num_steps is None or steps < num_steps:
        if cursor + step_rows > n_total:
            if wrap:
                cursor = 0
            else:
                tail = n_total - cursor
                if tail and remainder == "error":
                    raise ValueError(
                        f"{tail} remainder rows (step={step_rows}); set "
                        "remainder='drop'/'pad' or adjust sizes"
                    )
                if tail and remainder == "pad":
                    block = np.zeros((step_rows, d), dtype=data.dtype)
                    block[:tail] = data[cursor:]
                    yield _place(
                        block.reshape(num_workers, rows_per_worker, d),
                        dtype, device,
                    )
                break
        block = data[cursor : cursor + step_rows]
        cursor += step_rows
        steps += 1
        yield _place(
            block.reshape(num_workers, rows_per_worker, d), dtype, device
        )


def synthetic_stream(
    spectrum,
    *,
    num_workers: int,
    rows_per_worker: int,
    num_steps: int,
    seed: int = 0,
    dtype=jnp.float32,
) -> Iterator[jax.Array]:
    """Infinite-data analogue of :func:`block_stream`: fresh planted-spectrum
    draws each step (true online setting; also the benchmark feed)."""
    key = jax.random.PRNGKey(seed)
    for _ in range(num_steps):
        key, sub = jax.random.split(key)
        x = spectrum.sample(sub, num_workers * rows_per_worker, dtype=dtype)
        yield x.reshape(num_workers, rows_per_worker, -1)
