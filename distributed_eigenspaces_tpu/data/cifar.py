"""CIFAR-10 pickle loader — feature parity with reference ``load_data.py``.

Same on-disk format (the python-pickle CIFAR batches), same public result
``(data, filenames, labels)`` with data in (N, 32, 32, 3) layout, plus the
preprocessing the reference applied inline at ``distributed.py:170-173``
(channel-mean grayscale + flatten) made explicit and optional — the RGB
3072-d path is first-class because BASELINE.md's CIFAR config requires it
(SURVEY.md §2.2-B7).
"""

from __future__ import annotations

import glob
import os
import pickle

import numpy as np

# Reference `UNUSED_FILES` (load_data.py:5): non-batch files in the dir.
UNUSED_FILES = ("readme.html", "batches.meta")


def unpickle(path: str):
    """Decode one CIFAR batch pickle (reference ``load_data.py:8-15``)."""
    with open(path, "rb") as fo:
        return pickle.load(fo, encoding="bytes")


def _assemble(paths, negatives: bool):
    """vstack batches, reshape to (N, 32, 32, 3) (reference ``load_data.py:18-33``).

    ``negatives=True`` gives float32 NHWC; False gives the uint8 rollaxis
    path — both kept for parity.
    """
    chunks, filenames, labels = [], [], []
    for p in paths:
        d = unpickle(p)
        chunks.append(d[b"data"])
        filenames += list(d[b"filenames"])
        labels += list(d[b"labels"])
    data = np.vstack(chunks).reshape((-1, 3, 32, 32))
    if negatives:
        data = data.transpose(0, 2, 3, 1).astype(np.float32)
    else:
        data = np.rollaxis(data, 1, 4)
    return data, np.array(filenames), np.array(labels)


def load_CIFAR_10_data(data_dir: str, negatives: bool = False):
    """Reference-identical entry point (``load_data.py:36-50``): glob the dir,
    drop metadata files, return ``(data (N,32,32,3), filenames, labels)``."""
    paths = sorted(glob.glob(os.path.join(data_dir, "*")))
    skip = {os.path.join(data_dir, u) for u in UNUSED_FILES}
    paths = [p for p in paths if p not in skip]
    if not paths:
        raise FileNotFoundError(f"no CIFAR batch files under {data_dir!r}")
    return _assemble(paths, negatives)


def preprocess(
    images: np.ndarray, *, grayscale: bool = True, dtype=np.float32
) -> np.ndarray:
    """(N, H, W, C) images -> (N, d) feature rows.

    ``grayscale=True`` reproduces the reference CLI path
    (``distributed.py:170-173``): channel mean then flatten to H*W (1024-d
    for CIFAR). ``grayscale=False`` flattens all channels (3072-d), the
    BASELINE.md CIFAR config. uint8 input takes the native C++ conversion
    kernels (``native/loader.cc``); anything else the numpy path.
    """
    images = np.asarray(images)
    if images.dtype == np.uint8 and dtype == np.float32 and images.ndim == 4:
        from distributed_eigenspaces_tpu.runtime.native import (
            to_f32,
            to_gray_f32,
        )

        if grayscale:
            return to_gray_f32(images)
        return to_f32(images).reshape(images.shape[0], -1)
    x = images.astype(dtype)
    if grayscale:
        x = x.mean(axis=3)
    return x.reshape(x.shape[0], -1)


def load_cifar10(
    data_dir: str, *, grayscale: bool = True, dtype=np.float32
):
    """One-call loader: pickles -> (N, d) rows + labels, with the B7 toggle."""
    data, _, labels = load_CIFAR_10_data(data_dir)
    return preprocess(data, grayscale=grayscale, dtype=dtype), labels
