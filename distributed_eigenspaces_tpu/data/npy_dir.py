"""User-supplied row ingestion: a directory of ``.npy`` / flat ``.bin``
files -> an ``(N, dim)`` float32 row matrix (round-5 verdict item 7).

The scale-out configs (BASELINE 4: ImageNet 64x64 patches, 12288-d;
BASELINE 5: CLIP ViT-L embeddings, 768-d) have no downloadable dataset
on a zero-egress rig, but users HAVE these datasets — this module is the
ingestion path from "a directory of arrays I exported" to the eval
harness / estimator:

- ``*.npy``: either ``(N, dim)`` row matrices, or ``(N, ...)`` stacks
  whose trailing dimensions flatten to ``dim`` — e.g. ``(N, 64, 64, 3)``
  image patches for the 12288-d config (the patch-extraction contract:
  row-major flatten, exactly ``arr.reshape(N, -1)``).
- ``*.bin``: flat float32 rows, ``array.tobytes()`` of an ``(N, dim)``
  matrix — the same wire format ``data.bin_stream`` consumes/produces
  (so a corpus prepared with ``det-pca-quantize``'s float source file
  loads here too).

Files load in sorted-name order (deterministic row order), memory-mapped
and copied only up to ``max_rows`` — pointing this at a 1.2 TB corpus
and asking for one eval's worth of rows reads one eval's worth of bytes.

The reference's data story is "every process loads the full dataset from
a local directory" (``distributed.py:169``, ``load_data.py:36-50``);
this is that arrangement for arbitrary row data, bounded and checked.
"""

from __future__ import annotations

import os

import numpy as np


def load_rows_dir(
    directory: str,
    dim: int,
    *,
    max_rows: int | None = None,
) -> tuple[np.ndarray, dict]:
    """Load ``(N, dim)`` float32 rows from every ``.npy``/``.bin`` file
    under ``directory`` (sorted order). Returns ``(rows, provenance)``
    where provenance records the directory, per-file row counts, and
    total rows — the report-JSON evidence of what was actually read.

    Raises ``FileNotFoundError`` for a missing/empty directory and
    ``ValueError`` for files whose shape cannot yield ``dim``-wide rows
    (loud beats a silent reshape of the wrong data).
    """
    # listdir + suffix filter, NOT glob: a user path containing glob
    # metacharacters (~/data[v2]/...) would silently match nothing and
    # read as "no files" — triggering the check script's synthesize
    # fallback over the user's real corpus
    if not os.path.isdir(directory):
        raise FileNotFoundError(f"{directory!r} is not a directory")
    paths = sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if name.endswith((".npy", ".bin"))
    )
    if not paths:
        raise FileNotFoundError(
            f"no .npy or .bin row files under {directory!r}"
        )
    chunks: list[np.ndarray] = []
    files: list[dict] = []
    total = 0
    for path in paths:
        if max_rows is not None and total >= max_rows:
            break
        if path.endswith(".npy"):
            arr = np.load(path, mmap_mode="r")
            if arr.ndim < 2:
                raise ValueError(
                    f"{path}: need (N, ...) stacks, got shape {arr.shape}"
                )
            width = int(np.prod(arr.shape[1:]))
            if width != dim:
                raise ValueError(
                    f"{path}: rows flatten to {width} values, config "
                    f"needs dim={dim} (shape {arr.shape})"
                )
            n_file = arr.shape[0]
            take = (
                n_file if max_rows is None
                else min(n_file, max_rows - total)
            )
            # mmap -> copy of exactly the consumed slice, flattened to rows
            chunk = np.asarray(
                arr[:take], dtype=np.float32
            ).reshape(take, dim)
        else:  # .bin: flat float32 rows
            size = os.path.getsize(path)
            row_bytes = dim * 4
            if size == 0 or size % row_bytes:
                raise ValueError(
                    f"{path}: {size} bytes is not a whole number of "
                    f"float32 rows of dim={dim}"
                )
            n_file = size // row_bytes
            take = (
                n_file if max_rows is None
                else min(n_file, max_rows - total)
            )
            chunk = np.fromfile(
                path, dtype=np.float32, count=take * dim
            ).reshape(take, dim)
        chunks.append(chunk)
        files.append({"file": os.path.basename(path), "rows": int(take)})
        total += take
    rows = np.concatenate(chunks, axis=0) if len(chunks) > 1 else chunks[0]
    provenance = {
        "dir": os.path.abspath(directory),
        "files": files,
        "rows": int(total),
        "dim": int(dim),
    }
    return rows, provenance
