"""MNIST IDX loader (BASELINE.md eval config 3: MNIST-784 streaming).

The reference ships only a CIFAR pickle loader (``load_data.py:8-50``); the
MNIST config in BASELINE.json needs the classic IDX format (the
``train-images-idx3-ubyte`` files from yann.lecun.com), which this module
parses directly — magic header, big-endian dims, raw ubyte payload —
with transparent ``.gz`` support and the same ``(data, labels)`` return
shape as :func:`..cifar.load_cifar10`.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.dtype(">i2"),
    0x0C: np.dtype(">i4"),
    0x0D: np.dtype(">f4"),
    0x0E: np.dtype(">f8"),
}


def read_idx(path: str) -> np.ndarray:
    """Parse one IDX file (optionally gzipped) into a numpy array."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zeros, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zeros != 0 or dtype_code not in _IDX_DTYPES:
            raise ValueError(f"{path}: not an IDX file (magic {zeros:#x} "
                             f"{dtype_code:#x})")
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        dt = _IDX_DTYPES[dtype_code]
        raw = f.read()
    n_items = int(np.prod(dims)) if dims else 0
    arr = np.frombuffer(raw, dtype=dt, count=n_items)
    return arr.reshape(dims)


def _find(data_dir: str, stem: str) -> str | None:
    for name in (stem, stem + ".gz", stem.replace("-idx", ".idx"),
                 stem.replace("-idx", ".idx") + ".gz"):
        p = os.path.join(data_dir, name)
        if os.path.exists(p):
            return p
    return None


def load_mnist(
    data_dir: str,
    *,
    split: str = "train",
    flatten: bool = True,
    dtype=np.float32,
) -> tuple[np.ndarray, np.ndarray]:
    """Load MNIST: ``(N, 784) float32`` images (pixel values 0..255, like
    the CIFAR loader keeps raw scale) plus ``(N,)`` integer labels.

    ``split`` is ``"train"`` (60k) or ``"test"``/``"t10k"`` (10k).
    """
    prefix = "train" if split == "train" else "t10k"
    img_path = _find(data_dir, f"{prefix}-images-idx3-ubyte")
    lbl_path = _find(data_dir, f"{prefix}-labels-idx1-ubyte")
    if img_path is None or lbl_path is None:
        raise FileNotFoundError(
            f"MNIST IDX files for split {split!r} not found in {data_dir}"
        )
    images = read_idx(img_path)
    labels = read_idx(lbl_path).astype(np.int64)
    if images.ndim != 3:
        raise ValueError(f"{img_path}: expected (N, 28, 28), got "
                         f"{images.shape}")
    if flatten:
        images = images.reshape(images.shape[0], -1)
    return images.astype(dtype), labels


def write_idx(path: str, arr: np.ndarray) -> None:
    """Write an array as an IDX file (test fixtures / dataset prep)."""
    codes = {np.dtype(np.uint8): 0x08, np.dtype(np.int8): 0x09}
    code = codes.get(arr.dtype)
    if code is None:
        raise ValueError(f"unsupported IDX dtype {arr.dtype}")
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(struct.pack(">HBB", 0, code, arr.ndim))
        f.write(struct.pack(f">{arr.ndim}I", *arr.shape))
        f.write(np.ascontiguousarray(arr).tobytes())
