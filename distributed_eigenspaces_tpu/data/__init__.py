"""Data layer (L1 of the reference layer map): loaders + sharded streaming.

Feature parity with reference ``load_data.py:1-76`` (CIFAR pickle loading)
plus what the reference lacked: a synthetic planted-spectrum generator (the
correctness config in BASELINE.md), MNIST-like streaming, and an explicit
batcher remainder policy (the reference silently dropped the tail,
``distributed.py:99-104`` — SURVEY.md §2.2-B5).
"""

from distributed_eigenspaces_tpu.data.cifar import (
    unpickle,
    load_cifar10,
    load_CIFAR_10_data,
    preprocess,
)
from distributed_eigenspaces_tpu.data.synthetic import (
    planted_spectrum,
    PlantedSpectrum,
)
from distributed_eigenspaces_tpu.data.stream import (
    block_stream,
    make_batches,
    synthetic_stream,
)
from distributed_eigenspaces_tpu.data.mnist import load_mnist, read_idx
from distributed_eigenspaces_tpu.data.bin_stream import (
    bin_block_stream,
    write_rows,
)

__all__ = [
    "load_mnist",
    "read_idx",
    "bin_block_stream",
    "write_rows",
    "unpickle",
    "load_cifar10",
    "load_CIFAR_10_data",
    "preprocess",
    "planted_spectrum",
    "PlantedSpectrum",
    "block_stream",
    "make_batches",
    "synthetic_stream",
]
