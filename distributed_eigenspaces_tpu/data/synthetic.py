"""Synthetic planted-spectrum Gaussian data — the correctness reference.

BASELINE.md config 2: "Synthetic Gaussian with planted spectrum, 1024-d,
top-5". The generator draws ``x = z @ diag(sqrt(lambda)) @ Q^T`` with a known
orthonormal basis ``Q`` and eigenvalue spectrum ``lambda``, so the true
principal subspace is available exactly and principal-angle assertions are
possible without an O(d^3) oracle run (the reference had no such config —
its only oracle was a visual sklearn comparison, notebook cells 21-22).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class PlantedSpectrum(NamedTuple):
    basis: jax.Array  # (d, d) orthonormal columns, descending eigenvalue order
    eigenvalues: jax.Array  # (d,) descending

    def top_k(self, k: int) -> jax.Array:
        """True top-k principal subspace (d, k)."""
        return self.basis[:, :k]

    def sample(self, key: jax.Array, n: int, dtype=jnp.float32) -> jax.Array:
        """Draw n rows with covariance ``Q diag(lambda) Q^T``."""
        d = self.basis.shape[0]
        z = jax.random.normal(key, (n, d), dtype=jnp.float32)
        x = (z * jnp.sqrt(self.eigenvalues)[None, :]) @ self.basis.T
        return x.astype(dtype)


def planted_spectrum(
    d: int,
    *,
    k_planted: int = 8,
    gap: float = 10.0,
    decay: float = 0.8,
    noise: float = 0.05,
    seed: int = 0,
) -> PlantedSpectrum:
    """Spectrum with ``k_planted`` strong directions over a noise floor.

    Leading eigenvalues: ``gap * decay**i`` for i < k_planted; the rest decay
    from ``noise`` — a clean eigengap so subspace recovery is well-posed.
    The basis is a Haar-random orthogonal matrix (QR of Gaussian).
    """
    rng = np.random.default_rng(seed)
    q, r = np.linalg.qr(rng.standard_normal((d, d)))
    q = q * np.sign(np.diag(r))[None, :]  # Haar correction
    lead = gap * decay ** np.arange(k_planted)
    tail = noise * (0.99 ** np.arange(d - k_planted))
    lam = np.concatenate([lead, tail])
    return PlantedSpectrum(
        basis=jnp.asarray(q, jnp.float32),
        eigenvalues=jnp.asarray(lam, jnp.float32),
    )


class PlantedSubspace(NamedTuple):
    """Low-rank planted model: covariance ``Q diag(lam) Q^T + noise^2 I``
    with ``Q (d, r)`` orthonormal — the large-d twin of
    :class:`PlantedSpectrum`.

    Building :func:`planted_spectrum`'s full d x d Haar basis is O(d^3)
    (minutes at d=12288, BASELINE config 4); only the planted r directions
    are ever needed for sampling or for the principal-angle oracle, so this
    keeps O(d*r) state and samples in O(n*(d + r^2)) — and entirely on
    device, which matters when the host link is slow.
    """

    basis: jax.Array  # (d, r) orthonormal, descending eigenvalue order
    eigenvalues: jax.Array  # (r,) descending, on top of the noise floor
    noise: float

    def top_k(self, k: int) -> jax.Array:
        """True top-k principal subspace (d, k); requires k <= r."""
        if k > self.basis.shape[1]:
            raise ValueError(
                f"k={k} exceeds planted rank {self.basis.shape[1]}"
            )
        return self.basis[:, :k]

    def sample(self, key: jax.Array, n: int, dtype=jnp.float32) -> jax.Array:
        """Draw n rows with covariance ``Q diag(lam) Q^T + noise^2 I``."""
        d, r = self.basis.shape
        kz, kn = jax.random.split(key)
        z = jax.random.normal(kz, (n, r), dtype=jnp.float32)
        x = (z * jnp.sqrt(self.eigenvalues)[None, :]) @ self.basis.T
        x = x + self.noise * jax.random.normal(kn, (n, d), dtype=jnp.float32)
        return x.astype(dtype)


def planted_subspace(
    d: int,
    *,
    k_planted: int = 8,
    gap: float = 10.0,
    decay: float = 0.8,
    noise: float = 0.05,
    seed: int = 0,
) -> PlantedSubspace:
    """Low-rank planted-subspace model (see :class:`PlantedSubspace`).

    Same leading spectrum as :func:`planted_spectrum` (``gap * decay**i``)
    sitting on an isotropic ``noise``-level floor; the true top-k subspace is
    exact for any ``k <= k_planted``.
    """
    rng = np.random.default_rng(seed)
    q, r = np.linalg.qr(rng.standard_normal((d, k_planted)))
    q = q * np.sign(np.diag(r))[None, :]
    lead = gap * decay ** np.arange(k_planted)
    return PlantedSubspace(
        basis=jnp.asarray(q, jnp.float32),
        eigenvalues=jnp.asarray(lead, jnp.float32),
        noise=float(noise),
    )
