"""Parallel-deflation eigensolve: model parallelism over k (ISSUE 18).

The last unparallelized loop in the system — component work inside one
eigensolve — becomes a mesh axis, after the parallel-deflation scheme
of *Provable Model-Parallel Distributed PCA with Parallel Deflation*
(arxiv 2502.17615): the k eigenvector columns split into L equal-width
LANES that iterate **concurrently**, each lane running blocked power /
subspace iteration against the same matvec operand while receiving
deflation corrections from the lanes below it. Lane 0 converges to the
leading block exactly as plain subspace iteration would; lane ``l``
iterates on the operator deflated by the *current* (still-moving)
estimates of lanes ``j < l`` — the paper's point is that this coupled
concurrent schedule still converges, so k-wide solves stop paying the
sequential-k critical path.

Wire discipline (the PR 13/15 sharding contracts, unchanged):

- corrections are exchanged as **k x k blocks** — lane ``l`` receives
  the kb x kb coefficient panels ``V_j^T (A V_l)`` (kb = k / L), never
  a d x d, never an above-floor replicated d x k;
- the only d-proportional collective is the **(d_local, kb) lane
  gather** over the ``components`` axis (feature-sharded rows, so no
  device ever holds an un-sharded full-d buffer);
- orthonormalization and the finishing Rayleigh–Ritz reuse the
  distributed solver's CholeskyQR2 / ``dist_rayleigh_ritz`` /
  sign-canonicalization verbatim — ONE definition of the numerics.

Two implementations of the same schedule:

- :func:`deflation_eig` — lanes BATCHED on one device (a ``(L,
  d_local, kb)`` stack), rows optionally sharded over ``features``.
  This is the dispatch route for ``cfg.solver="deflation"`` merges /
  extracts (``components_axis_size`` sets L) and the reference the
  mesh version is gated against.
- :func:`dist_deflation_eig` — lanes SHARDED over the ``components``
  mesh axis (``parallel/mesh.make_component_mesh``), one lane per mesh
  slot, composing with ``features`` row sharding. Audited by the
  ``deflation_solve`` contract (``analysis/contracts.py``).

On top of the lanes, **elastic k** (:func:`grow_directions` /
:func:`grow_basis`): widening a published basis k -> k' deflates
against the frozen parent — a single always-converged lane — and fits
only the k' - k new directions, so a tenant widening its basis never
pays a full refit. The serving tier publishes the result as a
lineage-linked version (``EigenbasisRegistry.publish_grown``).

Everything traces inside any caller's ``jit``/``shard_map``; all
solves are deterministic given ``key``. ``tol`` arms the same
gap-adaptive stop as :func:`~.distributed.dist_subspace_eig`, with
PER-LANE residuals and iteration counters (``with_info=True``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_eigenspaces_tpu.parallel.feature_sharded import (
    _collective_ops,
    _psum_if,
    chol_qr2,
)
from distributed_eigenspaces_tpu.parallel.mesh import (
    COMPONENT_AXIS,
    FEATURE_AXIS,
)
from distributed_eigenspaces_tpu.solvers.distributed import (
    HP,
    _scaled_factor_concat,
    dist_rayleigh_ritz,
    factor_matvec,
)

__all__ = [
    "deflation_eig",
    "dist_deflation_eig",
    "dist_merged_top_k_deflation",
    "grow_basis",
    "grow_directions",
    "merged_top_k_deflation",
]


def _lane_widths(k: int, lanes: int) -> int:
    """Validated equal lane width kb = k / lanes (loud, static)."""
    if not isinstance(lanes, int) or lanes < 1:
        raise ValueError(f"lanes must be an int >= 1, got {lanes!r}")
    if lanes > k:
        raise ValueError(
            f"lanes={lanes} exceeds k={k}: each deflation lane owns at "
            "least one eigenvector column"
        )
    if k % lanes:
        raise ValueError(
            f"k={k} must split into {lanes} equal-width lanes "
            "(equal widths keep the correction blocks k x k and the "
            "lane layout static)"
        )
    return k // lanes


def _lanes_to_flat(vs: jax.Array) -> jax.Array:
    """``(L, d_local, kb) -> (d_local, L*kb)`` with lane ``l`` owning
    columns ``[l*kb, (l+1)*kb)`` — eigenvalue-descending lane order."""
    return jnp.transpose(vs, (1, 0, 2)).reshape(vs.shape[1], -1)


def _flat_to_lanes(v: jax.Array, lanes: int) -> jax.Array:
    """Inverse of :func:`_lanes_to_flat`."""
    d, k = v.shape
    return jnp.transpose(v.reshape(d, lanes, k // lanes), (1, 0, 2))


def _lane_residuals(vs, ws, axis_name):
    """Per-lane relative invariance residual ``||W_l - V_l (V_l^T
    W_l)||_F / ||W_l||_F`` for lane stacks ``(L, d_local, kb)`` —
    kb x kb + scalar psums only. A dead lane (zero ``W_l``, the
    all-masked merge's guard) reads as converged (residual 0)."""
    s = jnp.einsum("ldb,ldc->lbc", vs, ws, precision=HP)
    s = _psum_if(s, axis_name)
    r = ws - jnp.einsum("ldb,lbc->ldc", vs, s, precision=HP)
    rn = _psum_if(jnp.sum(r * r, axis=(1, 2)), axis_name)
    wn = _psum_if(jnp.sum(ws * ws, axis=(1, 2)), axis_name)
    return jnp.sqrt(rn) / jnp.sqrt(jnp.maximum(wn, 1e-30))


def deflation_eig(
    matvec,
    d_local: int,
    k: int,
    *,
    lanes: int,
    iters: int = 16,
    tol: float | None = None,
    key: jax.Array | None = None,
    axis_name: str | None = None,
    v0: jax.Array | None = None,
    with_info: bool = False,
):
    """Top-k invariant subspace by PARALLEL DEFLATION with the L lanes
    batched on-device: a ``(L, d_local, kb)`` lane stack iterates
    concurrently, lane ``l`` deflating the current estimates of lanes
    ``j < l`` each sweep via kb x kb correction panels.

    Per iteration, for every lane at once: one matvec (columns are
    independent, so all lanes ride ONE operator application), the
    strictly-lower-triangular correction ``W_l -= sum_{j<l} V_j
    (V_j^T W_l)`` (one ``(L, L, kb, kb)`` einsum, reduced over
    ``axis_name`` with a k x k-class psum), and a per-lane CholeskyQR2.
    The finish assembles the lanes into ``(d_local, k)``, re-runs
    CholeskyQR2 across lanes (cross-lane drift from still-moving lower
    lanes is second-order, but free to remove), and applies the shared
    Rayleigh–Ritz + sign canonicalization — so the output contract is
    exactly :func:`~.distributed.dist_subspace_eig`'s: descending
    eigenvalue order, globally canonical signs, a ``(d_local, k)`` row
    shard.

    ``tol`` arms the PER-LANE gap-adaptive stop: a lane whose measured
    residual drops below ``tol`` freezes (its blocks stop updating —
    converged lower lanes keep feeding corrections from their frozen
    values, the deflation semantics), and the loop ends when every
    lane froze or at ``iters``. ``with_info=True`` returns ``(v,
    info)`` with per-lane ``iters_used`` / ``residual`` vectors — the
    convergence counters ``MetricsLogger.summary()`` surfaces."""
    kb = _lane_widths(k, lanes)
    if key is None:
        key = jax.random.PRNGKey(0)
    if axis_name is not None:
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    v = jax.random.normal(key, (d_local, k), jnp.float32)
    if v0 is not None:
        d_total = _psum_if(jnp.asarray(d_local, jnp.float32), axis_name)
        v = (1e-3 * lax.rsqrt(d_total)) * v
        v = v.at[:, : v0.shape[1]].add(v0)
    # cross-lane orthonormal start (one full-width CholeskyQR2), then
    # split into the lane stack
    vs = _flat_to_lanes(chol_qr2(v, axis_name), lanes)
    lower = (
        jnp.arange(lanes)[:, None] < jnp.arange(lanes)[None, :]
    ).astype(jnp.float32)[:, :, None, None]  # strict: j < l

    def sweep(vs, active):
        # ONE matvec application covers every lane (column-independent)
        ws = _flat_to_lanes(matvec(_lanes_to_flat(vs)), lanes)
        # deflation corrections: kb x kb panels V_j^T W_l, j < l
        coef = jnp.einsum("jdb,ldc->jlbc", vs, ws, precision=HP)
        coef = _psum_if(coef, axis_name) * lower
        ws = ws - jnp.einsum("jdb,jlbc->ldc", vs, coef, precision=HP)
        res = _lane_residuals(vs, ws, axis_name)
        vn = chol_qr2(ws, axis_name)  # batched per-lane QR
        gate = active[:, None, None]
        return jnp.where(gate > 0, vn, vs), res

    if tol is None:
        ones = jnp.ones((lanes,), jnp.float32)
        vs = lax.fori_loop(
            0, iters, lambda _, s: sweep(s, ones)[0], vs
        )
        iters_used = jnp.full((lanes,), iters, jnp.int32)
        res = jnp.full((lanes,), jnp.nan, jnp.float32)
    else:

        def cond(carry):
            _, i, res, _ = carry
            return jnp.logical_and(i < iters, jnp.any(res > tol))

        def body(carry):
            vs, i, res, used = carry
            active = (res > tol).astype(jnp.float32)
            vs, res = sweep(vs, active)
            used = used + (active > 0).astype(jnp.int32)
            return vs, i + 1, res, used

        vs, _, res, iters_used = lax.while_loop(
            cond,
            body,
            (
                vs,
                jnp.asarray(0, jnp.int32),
                jnp.full((lanes,), jnp.inf, jnp.float32),
                jnp.zeros((lanes,), jnp.int32),
            ),
        )
    flat = chol_qr2(_lanes_to_flat(vs), axis_name)
    out = dist_rayleigh_ritz(flat, matvec(flat), axis_name)[:, :k]
    if with_info:
        return out, {
            "iters_used": iters_used, "residual": res,
            "lanes": lanes, "lane_width": kb,
        }
    return out


def dist_deflation_eig(
    matvec,
    d_local: int,
    k: int,
    *,
    lanes: int,
    iters: int = 16,
    tol: float | None = None,
    key: jax.Array | None = None,
    lane_axis: str = COMPONENT_AXIS,
    axis_name: str | None = FEATURE_AXIS,
    v0: jax.Array | None = None,
    with_info: bool = False,
    wire_dtype: str = "fp32",
):
    """:func:`deflation_eig` with the lanes SHARDED over the
    ``components`` mesh axis — call inside ``shard_map`` over a
    ``(components, features)`` mesh (``make_component_mesh``), one
    lane of width kb = k / lanes per components slot. ``lanes`` must
    equal the mesh's ``components`` axis size (static — it sizes the
    lane blocks).

    The collective schedule per iteration, per device:

    - ONE ``all_gather`` of the own ``(d_local, kb)`` lane block over
      ``components`` — the (d, k)-class lane gather (feature-sharded
      rows: never an above-floor replicated d x k);
    - the kb x kb correction panels ``V_j^T (A V_l)`` reduced over
      ``features`` (one ``(L, kb, kb)`` psum — the k x k correction
      blocks);
    - CholeskyQR2's two kb x kb Gram psums over ``features``.

    The finish gathers the lanes once more, re-orthonormalizes across
    lanes, and runs the shared Rayleigh–Ritz — every components slot
    computes the identical ``(d_local, k)`` result (replicated over
    ``components``, row-sharded over ``features``).

    ``tol`` freezes THIS lane once its residual clears the bar while
    lower lanes keep feeding corrections; the loop runs until every
    lane froze (a scalar all-lanes reduce over ``components``) or
    ``iters``. ``with_info=True`` returns this lane's own counter —
    gather over ``lane_axis`` outside for the per-lane vector.

    ``v0`` warm-starts THIS lane from a ``(d_local, kb)`` seed block
    (e.g. the matching columns of a published basis on a hot swap) —
    it enters through CholeskyQR2, so any full-rank block is legal.

    ``wire_dtype`` ships the cross-lane panel gathers — the per-sweep
    ``(L, d_local, kb)`` lane stack and the finishing gather, the
    solve's only d-wide payloads — in {fp32, bf16, int8} through the
    ``parallel/wire.py`` codecs (ISSUE 20). One-shot lossy: the sweep
    is self-correcting (each iteration re-gathers and CholeskyQR2
    re-orthonormalizes), and the correction/Gram psums stay fp32."""
    from distributed_eigenspaces_tpu.parallel.wire import (
        wire_all_gather,
    )

    def lane_gather(x):
        if wire_dtype == "fp32":
            return lax.all_gather(x, lane_axis)
        return wire_all_gather(x, lane_axis, wire_dtype, tiled=False)

    kb = _lane_widths(k, lanes)
    my = lax.axis_index(lane_axis)
    if v0 is not None:
        v = chol_qr2(v0.astype(jnp.float32), axis_name)
    else:
        if key is None:
            key = jax.random.PRNGKey(0)
        if axis_name is not None:
            key = jax.random.fold_in(key, lax.axis_index(axis_name))
        key = jax.random.fold_in(key, my)
        v = chol_qr2(
            jax.random.normal(key, (d_local, kb), jnp.float32),
            axis_name,
        )
    jlt = jnp.arange(lanes)  # lane indices, for the j < my mask

    def sweep(v, active):
        vs = lane_gather(v)  # (L, d_local, kb)
        w = matvec(v)  # (d_local, kb)
        coef = jnp.einsum("jdb,dc->jbc", vs, w, precision=HP)
        coef = _psum_if(coef, axis_name)
        coef = coef * (jlt < my).astype(coef.dtype)[:, None, None]
        w = w - jnp.einsum("jdb,jbc->dc", vs, coef, precision=HP)
        # this lane's residual (kb-wide + scalar psums over features)
        s = jnp.matmul(v.T, w, precision=HP)
        s = _psum_if(s, axis_name)
        r = w - jnp.matmul(v, s, precision=HP)
        rn = _psum_if(jnp.sum(r * r), axis_name)
        wn = _psum_if(jnp.sum(w * w), axis_name)
        res = jnp.sqrt(rn) / jnp.sqrt(jnp.maximum(wn, 1e-30))
        vn = chol_qr2(w, axis_name)
        return jnp.where(active > 0, vn, v), res

    if tol is None:
        one = jnp.asarray(1.0, jnp.float32)
        v = lax.fori_loop(0, iters, lambda _, s: sweep(s, one)[0], v)
        iters_used = jnp.asarray(iters, jnp.int32)
        res = jnp.asarray(jnp.nan, jnp.float32)
    else:

        def cond(carry):
            _, i, _, _, worst = carry
            # the carried all-lanes max keeps the collective out of
            # the while cond (body-side pmax over components)
            return jnp.logical_and(i < iters, worst > tol)

        def body(carry):
            v, i, res, used, _ = carry
            active = (res > tol).astype(jnp.float32)
            v, res = sweep(v, active)
            used = used + (active > 0).astype(jnp.int32)
            worst = lax.pmax(res, lane_axis)
            return v, i + 1, res, used, worst

        v, _, res, iters_used, _ = lax.while_loop(
            cond,
            body,
            (
                v,
                jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, jnp.float32),
                jnp.asarray(0, jnp.int32),
                jnp.asarray(jnp.inf, jnp.float32),
            ),
        )
    vs = lane_gather(v)  # the finishing lane gather
    flat = chol_qr2(_lanes_to_flat(vs), axis_name)
    out = dist_rayleigh_ritz(flat, matvec(flat), axis_name)[:, :k]
    if with_info:
        return out, {"iters_used": iters_used, "residual": res,
                     "lanes": lanes, "lane_width": kb}
    return out


def merged_top_k_deflation(
    v_stack: jax.Array,
    k: int,
    *,
    lanes: int,
    mask: jax.Array | None = None,
    iters: int = 16,
    tol: float | None = None,
    key: jax.Array | None = None,
    v0: jax.Array | None = None,
):
    """The MERGE solve on the deflation route: top-k of the (masked)
    mean worker projector from a full ``(m, d, k_f)`` factor stack, by
    parallel-deflation lanes on the factor operator ``C C^T`` — the
    ``cfg.solver="deflation"`` twin of
    :func:`~.distributed.merged_top_k_distributed` (same operand, same
    guard semantics: an all-masked round returns exact zeros). ``v0``
    warm-starts the lane stack from the previous merged basis."""
    m = v_stack.shape[0]
    if mask is None:
        w = jnp.ones((m,), jnp.float32)
    else:
        w = mask.astype(jnp.float32)
    alive = jnp.sum(w) > 0
    cc = _scaled_factor_concat(v_stack, w)
    mv = factor_matvec(cc, None, alive=alive)
    v = deflation_eig(
        mv, v_stack.shape[1], k, lanes=lanes, iters=iters, tol=tol,
        key=key, axis_name=None, v0=v0,
    )
    return v * alive.astype(v.dtype)


def dist_merged_top_k_deflation(
    v_workers: jax.Array,
    k: int,
    *,
    lanes: int,
    mask: jax.Array | None = None,
    iters: int = 16,
    tol: float | None = None,
    key: jax.Array | None = None,
    collectives: str = "xla",
    v0: jax.Array | None = None,
    wire_dtype: str = "fp32",
):
    """The deflation merge inside ``shard_map`` over the ``(workers,
    features)`` mesh — the ``cfg.solver="deflation"`` twin of
    :func:`~.distributed.dist_merged_top_k`: same worker-axis factor
    gather and masked factor operand, but the crossover eigensolve runs
    the parallel-deflation lanes (batched per device, rows sharded over
    ``features``) instead of plain subspace iteration. ``v0`` row shard
    warm-starts the lane stack; an all-masked round returns exact
    zeros. ``wire_dtype`` compresses the worker factor-stack gather
    exactly as in ``dist_merged_top_k`` (one-shot lossy; mask gather
    and psums stay fp32; xla collectives only)."""
    _, gather_c = _collective_ops(collectives)
    from distributed_eigenspaces_tpu.parallel.mesh import WORKER_AXIS

    if wire_dtype != "fp32":
        if collectives != "xla":
            raise ValueError(
                "wire_dtype compression needs collectives='xla' (the "
                "ring route has no codec path)"
            )
        from distributed_eigenspaces_tpu.parallel.wire import (
            wire_all_gather,
        )

        c = wire_all_gather(
            v_workers, WORKER_AXIS, wire_dtype, tiled=True
        )
    else:
        c = gather_c(v_workers, WORKER_AXIS)  # (m_total, d_local, kf)
    m_total = c.shape[0]
    if mask is None:
        w = jnp.ones((m_total,), jnp.float32)
    else:
        w = gather_c(mask, WORKER_AXIS).astype(jnp.float32)
    alive = jnp.sum(w) > 0
    cc = _scaled_factor_concat(c, w)
    mv = factor_matvec(cc, FEATURE_AXIS, alive=alive)
    v = deflation_eig(
        mv, c.shape[1], k, lanes=lanes, iters=iters, tol=tol, key=key,
        axis_name=FEATURE_AXIS, v0=v0,
    )
    return v * alive.astype(v.dtype)


def grow_directions(
    matvec,
    v_parent: jax.Array,
    k_new: int,
    *,
    iters: int = 16,
    tol: float | None = None,
    key: jax.Array | None = None,
    axis_name: str | None = None,
    with_info: bool = False,
):
    """Elastic k, the solve half: fit ``k_new`` directions ORTHOGONAL
    to a frozen parent basis ``v_parent (d_local, k0)`` — deflated
    subspace iteration where the parent is a single permanently-
    converged lane: every sweep applies ``W -= V_p (V_p^T W)`` (a
    k0 x k_new correction block, reduced over ``axis_name``) before
    the CholeskyQR2, so the new block converges to eigenpairs
    ``k0+1 .. k0+k_new`` of the operator without ever re-fitting the
    parent's span. Finish: Rayleigh–Ritz of the new block alone
    (deflated operator), descending order, canonical signs."""
    if key is None:
        key = jax.random.PRNGKey(0)
    if axis_name is not None:
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    d_local = v_parent.shape[0]
    v = jax.random.normal(key, (d_local, k_new), jnp.float32)

    def deflate(w):
        coef = jnp.matmul(v_parent.T, w, precision=HP)
        coef = _psum_if(coef, axis_name)
        return w - jnp.matmul(v_parent, coef, precision=HP)

    v = chol_qr2(deflate(v), axis_name)

    def sweep(vi):
        w = deflate(matvec(vi))
        return w, chol_qr2(w, axis_name)

    if tol is None:
        v = lax.fori_loop(0, iters, lambda _, vi: sweep(vi)[1], v)
        iters_used = jnp.asarray(iters, jnp.int32)
        res = jnp.asarray(jnp.nan, jnp.float32)
    else:
        from distributed_eigenspaces_tpu.solvers.distributed import (
            subspace_residual,
        )

        def cond(carry):
            _, i, res = carry
            return jnp.logical_and(i < iters, res > tol)

        def body(carry):
            vi, i, _ = carry
            w, vn = sweep(vi)
            return vn, i + 1, subspace_residual(vi, w, axis_name)

        v, iters_used, res = lax.while_loop(
            cond, body, (v, jnp.asarray(0, jnp.int32),
                         jnp.asarray(jnp.inf, jnp.float32))
        )
    out = dist_rayleigh_ritz(v, deflate(matvec(v)), axis_name)
    if with_info:
        return out, {"iters_used": iters_used, "residual": res}
    return out


def grow_basis(
    matvec,
    v_parent: jax.Array,
    k_prime: int,
    *,
    iters: int = 16,
    tol: float | None = None,
    key: jax.Array | None = None,
    axis_name: str | None = None,
    with_info: bool = False,
):
    """Elastic k end-to-end on the solver side: widen a converged
    parent basis ``(d_local, k0)`` to ``(d_local, k_prime)`` by
    fitting ONLY the ``k_prime - k0`` new directions
    (:func:`grow_directions`) and concatenating — the first k0 columns
    of the result ARE the parent, bit-identical, so a serving tier
    that validated the parent needs to validate only the suffix. The
    fit cost is ``O((k' - k))`` matvec columns per sweep vs a full
    refit's ``O(k')`` — the elastic-k product claim ``bench.py
    --deflate`` measures. Publish the result through
    ``EigenbasisRegistry.publish_grown`` to get the lineage-linked
    version the replication fleet tails."""
    k0 = v_parent.shape[1]
    if not k0 < k_prime:
        raise ValueError(
            f"grow_basis needs k_prime > parent k, got k_prime="
            f"{k_prime} vs parent k={k0} (shrinking is a slice, not a "
            "fit)"
        )
    new = grow_directions(
        matvec, v_parent, k_prime - k0, iters=iters, tol=tol, key=key,
        axis_name=axis_name, with_info=with_info,
    )
    if with_info:
        new, info = new
        return jnp.concatenate([v_parent, new], axis=1), info
    return jnp.concatenate([v_parent, new], axis=1)
