"""Distributed blocked subspace eigensolve over the ``features`` axis.

The building blocks the rest of the system composes (ISSUE 15):

- :func:`dist_subspace_eig` — blocked randomized subspace iteration on a
  row-sharded operator: every iterate is a ``(d_local, k)`` row shard,
  orthonormalized globally by CholeskyQR2 (k x k Gram ``psum`` — the
  in-tree row-sharded pass), finished by :func:`dist_rayleigh_ritz`
  (one k x k ``psum`` + a replicated k-sized ``eigh`` + a row-local
  rotation). The only cross-device payloads are k-wide.
- :func:`dist_merged_top_k` — the MERGE solve on the feature-sharded
  mesh: top-k of the masked mean worker projector from its gathered
  factors, as subspace iteration on ``C C^T`` (``C`` the scaled factor
  concatenation, row-sharded). Replaces the ``(m*k)^2`` replicated
  Gram eigh of ``merged_lowrank_sharded`` above the crossover — the
  psum payloads stay ``(m*k) x k``.
- :func:`merged_top_k_distributed` — the same factor-operator solve on
  an UNSHARDED ``(m, d, k)`` stack (``axis_name=None`` degenerate):
  the root-tier merge of the tiered tree and the flat dense trainers'
  crossover route. Never forms the d x d mean projector and never the
  ``(m*k)^2`` Gram.
- :func:`dist_extract_top_k` — the SERVING extract: top-k of the
  running low-rank state ``U S U^T`` from its row-sharded factors,
  used at publish time above the crossover so the published basis is
  born sharded.

Everything traces inside any caller's ``jit``/``shard_map``; nothing
here is jitted at module scope. All solves are deterministic given
``key``/``v0``. Accuracy is the subspace-iteration geometric rate in
the eigengap — the crossover callers gate it against ``eigh`` ground
truth with the existing angle budget (tests/test_dist_solver.py,
``bench.py --dsolve``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_eigenspaces_tpu.ops.linalg import canonicalize_signs
from distributed_eigenspaces_tpu.parallel.feature_sharded import (
    _chol_apply,
    _chol_qr,
    _collective_ops,
    _psum_if,
    _small_eigh_desc,
    chol_qr2,
)
from distributed_eigenspaces_tpu.parallel.mesh import (
    FEATURE_AXIS,
    WORKER_AXIS,
)

HP = lax.Precision.HIGHEST

__all__ = [
    "dist_canonicalize_signs",
    "dist_extract_top_k",
    "dist_merged_top_k",
    "dist_rayleigh_ritz",
    "dist_subspace_eig",
    "factor_matvec",
    "fused_factor_matvec",
    "lowrank_matvec",
    "merged_top_k_distributed",
    "subspace_residual",
]


def dist_canonicalize_signs(v: jax.Array, axis_name: str | None = None):
    """Sign canonicalization of a row-sharded basis ``v (d_local, k)``:
    flip each column so its globally-largest-|entry| element is
    positive. The sharded twin of ``ops.linalg.canonicalize_signs`` —
    the pivot search gathers only a ``(2, k)`` candidate per shard
    (never the basis). Cross-shard |pivot| ties resolve to the lowest
    shard index (deterministic; the dense rule's first-index
    tie-break, per shard)."""
    if axis_name is None:
        return canonicalize_signs(v)
    idx = jnp.argmax(jnp.abs(v), axis=0)
    pivot = jnp.take_along_axis(v, idx[None, :], axis=0)[0]  # (k,)
    cand = jnp.stack([jnp.abs(pivot), pivot])  # (2, k)
    allc = lax.all_gather(cand, axis_name)  # (f, 2, k)
    shard = jnp.argmax(allc[:, 0, :], axis=0)  # (k,)
    gpivot = jnp.take_along_axis(allc[:, 1, :], shard[None, :], axis=0)[0]
    signs = jnp.where(gpivot >= 0, 1.0, -1.0).astype(v.dtype)
    return v * signs[None, :]


def dist_rayleigh_ritz(
    v: jax.Array, av: jax.Array, axis_name: str | None = None
):
    """Rotate a converged row-sharded orthonormal basis ``v (d_local,
    k)`` to eigenvector coordinates given ``av = A @ v``: the k x k
    projected operator reduces over ``features`` with one psum, the
    tiny eigh runs replicated, and the rotation is row-local —
    descending eigenvalue order, globally canonical signs (the
    ``ops.linalg.rayleigh_ritz`` semantics, sharded)."""
    small = jnp.matmul(v.T, av, precision=HP)
    small = _psum_if(small, axis_name)
    _, q = _small_eigh_desc(small)
    v = jnp.matmul(v, q, precision=HP)
    return dist_canonicalize_signs(v, axis_name)


def dist_subspace_eig(
    matvec,
    d_local: int,
    k: int,
    *,
    iters: int = 16,
    key: jax.Array | None = None,
    axis_name: str | None = FEATURE_AXIS,
    v0: jax.Array | None = None,
    oversample: int = 0,
    matvec_gram=None,
    tol: float | None = None,
    with_info: bool = False,
):
    """Top-k invariant subspace of a symmetric PSD operator by blocked
    randomized subspace iteration with the rows sharded over
    ``axis_name``.

    ``matvec(v) -> A @ v`` maps ``(d_local, k')`` row shards to row
    shards (reducing over ``axis_name`` internally as needed — see
    :func:`factor_matvec` / :func:`lowrank_matvec`). Per iteration: one
    matvec + one CholeskyQR2 (two k' x k' Gram psums); the tail is one
    Rayleigh–Ritz. ``oversample`` widens the iterated block to
    ``k' = k + oversample`` and truncates after the Rayleigh–Ritz sort
    — convergence is geometric in ``lambda_{k'+1}/lambda_k``, so a few
    extra columns buy orders of magnitude at small eigengaps for
    k-wide cost. ``v0 (d_local, k)`` warm-starts the leading block
    (blended with norm-matched noise, the ``worker_subspace_sharded``
    rule, so a zero ``v0`` degrades to the random init).
    ``axis_name=None`` runs the identical schedule unsharded — the
    root-tier / single-device degenerate.

    ``matvec_gram`` (``axis_name=None`` only, e.g.
    :func:`fused_factor_matvec`) fuses each inner sweep: it returns
    ``(w, g) = (matvec(v), w^T w)`` in one kernel and the loop
    finishes CholeskyQR2 from the precomputed Gram — same math, one
    launch and one fewer pass over the operator per iteration on
    TPU.

    ``tol`` (ISSUE 18 satellite) arms the gap-adaptive stop: the loop
    measures the subspace residual ``||W - V (V^T W)||_F / ||W||_F``
    (``W = A V``, one extra k' x k' psum + two scalar psums per
    iteration — never anything d-wide) and stops as soon as it drops
    below ``tol``, still bounded above by ``iters``. ``tol=None``
    compiles the exact fixed-``iters`` ``fori_loop`` program,
    byte-identical to the pre-knob build. ``with_info=True`` returns
    ``(v, info)`` with ``info = {"iters_used", "residual"}`` (traced
    scalars) so callers can surface convergence counters."""
    if matvec_gram is not None and axis_name is not None:
        raise ValueError(
            "matvec_gram fuses a LOCAL operator with its Gram; the "
            "sharded inner loop must psum between the matvec and the "
            "Gram, so fusion only applies with axis_name=None"
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    if axis_name is not None:
        # deterministic, shard-distinct init rows
        key = jax.random.fold_in(key, lax.axis_index(axis_name))
    kk = k + max(int(oversample), 0)
    v = jax.random.normal(key, (d_local, kk), jnp.float32)
    if v0 is not None:
        d_total = _psum_if(jnp.asarray(d_local, jnp.float32), axis_name)
        v = (1e-3 * lax.rsqrt(d_total)) * v
        v = v.at[:, :k].add(v0)
    v = chol_qr2(v, axis_name)

    if matvec_gram is None:

        def sweep(vi):
            w = matvec(vi)
            return w, chol_qr2(w, axis_name)

    else:

        def sweep(vi):
            w, g = matvec_gram(vi)
            # First CholeskyQR pass reuses the fused Gram; the second
            # recomputes it from the orthogonalised factor (QR2).
            return w, _chol_qr(_chol_apply(w, g), axis_name)

    if tol is None:
        v = lax.fori_loop(0, iters, lambda _, vi: sweep(vi)[1], v)
        iters_used = jnp.asarray(iters, jnp.int32)
        res = jnp.asarray(jnp.nan, jnp.float32)
    else:

        def cond(carry):
            _, i, res = carry
            return jnp.logical_and(i < iters, res > tol)

        def body(carry):
            vi, i, _ = carry
            w, vn = sweep(vi)
            res = subspace_residual(vi, w, axis_name)
            return vn, i + 1, res

        v, iters_used, res = lax.while_loop(
            cond, body, (v, jnp.asarray(0, jnp.int32),
                         jnp.asarray(jnp.inf, jnp.float32))
        )
    out = dist_rayleigh_ritz(v, matvec(v), axis_name)[:, :k]
    if with_info:
        return out, {"iters_used": iters_used, "residual": res}
    return out


def subspace_residual(v: jax.Array, w: jax.Array,
                      axis_name: str | None = None) -> jax.Array:
    """Relative invariance residual of an orthonormal row-sharded block
    ``v (d_local, k')`` given ``w = A @ v``: ``||W - V (V^T W)||_F /
    ||W||_F`` — the measured quantity the gap-adaptive stop compares to
    ``tol``. Payloads: one k' x k' psum + two scalar psums; nothing
    d-wide. Zero ``w`` (the all-masked merge's dead operator) yields
    residual 0, so a dead solve stops immediately instead of spinning
    to the iteration cap."""
    s = jnp.matmul(v.T, w, precision=HP)
    s = _psum_if(s, axis_name)
    r = w - jnp.matmul(v, s, precision=HP)
    rn = _psum_if(jnp.sum(r * r), axis_name)
    wn = _psum_if(jnp.sum(w * w), axis_name)
    return jnp.sqrt(rn) / jnp.sqrt(jnp.maximum(wn, 1e-30))


def factor_matvec(c: jax.Array, axis_name: str | None = None, alive=None):
    """``matvec(v) = C (C^T v)`` for a row-sharded factor concatenation
    ``C (d_local, f)`` — the mean-projector operator from its factors.
    The inner ``(f, k)`` product reduces over ``axis_name`` with a psum
    (f = m*k wide — never d). ``alive`` (traced bool) guards the
    all-masked merge: a zero ``C`` would feed CholeskyQR2 a zero Gram
    (NaN Cholesky), so the dead operator degrades to the identity and
    the caller zeroes the discarded result."""

    def matvec(v):
        y = jnp.matmul(c.T, v, precision=HP)
        y = _psum_if(y, axis_name)
        out = jnp.matmul(c, y, precision=HP)
        if alive is None:
            return out
        return jnp.where(alive, out, v)

    return matvec


def fused_factor_matvec(c: jax.Array, *, interpret: bool = False):
    """``matvec_gram(v) -> (w, g)`` for an UNSHARDED factor operator
    ``C (d, f)``: the inner-loop matvec ``w = C (C^T v)`` fused with
    the first Gram ``g = w^T w`` that CholeskyQR2 consumes — on TPU one
    Pallas launch (``ops.pallas_gram.matvec_gram_pallas``: two passes
    over C, the f x k partial resident in VMEM scratch, nothing d-wide
    materialized); elsewhere the identical-math XLA pair. The sharded
    operator cannot fuse across its cross-shard psum, so this is the
    local / root-tier fast path — :func:`dist_subspace_eig` takes the
    result via ``matvec_gram=`` and finishes CholeskyQR2 from ``g``."""

    def matvec_gram(v):
        on_tpu = jax.devices()[0].platform in ("tpu", "axon")
        if on_tpu or interpret:
            from distributed_eigenspaces_tpu.ops.pallas_gram import (
                _pick_block,
                matvec_gram_pallas,
            )

            bd = _pick_block(c.shape[0], 512, 8)
            if bd is not None:
                return matvec_gram_pallas(
                    c, v, block_d=bd, interpret=interpret
                )
        y = jnp.matmul(c.T, v, precision=HP)
        w = jnp.matmul(c, y, precision=HP)
        g = jnp.einsum("dk,dl->kl", w, w, precision=HP)
        return w, g

    return matvec_gram


def lowrank_matvec(u: jax.Array, s: jax.Array,
                   axis_name: str | None = None):
    """``matvec(v) = U diag(s) (U^T v)`` for a row-sharded low-rank
    state factorization ``U (d_local, r)``, ``s (r,)`` replicated —
    the serving-extract operator. Payload per psum: ``(r, k)``."""

    def matvec(v):
        y = jnp.matmul(u.T, v, precision=HP)
        y = _psum_if(y, axis_name)
        return jnp.matmul(u, jnp.maximum(s, 0.0)[:, None] * y,
                          precision=HP)

    return matvec


def _default_oversample(k: int, width: int) -> int:
    """Default block oversampling for the factor/state operators: a few
    extra iterated columns (capped by the operator's factor width — a
    wider block than the operator rank buys nothing) sharpen the
    geometric rate at small eigengaps for k-wide cost."""
    return max(min(8, width - k), 0)


def _scaled_factor_concat(c: jax.Array, w: jax.Array):
    """Scale a gathered factor stack ``c (m, d_local, kf)`` by the
    masked-mean weights and flatten to the concatenation ``C (d_local,
    m*kf)`` — the shared prologue of every factor merge (the
    ``merged_lowrank_sharded`` algebra)."""
    cnt = jnp.maximum(jnp.sum(w), 1.0)
    c = c * jnp.sqrt(w / cnt)[:, None, None]
    return jnp.transpose(c, (1, 0, 2)).reshape(c.shape[1], -1)


def dist_merged_top_k(
    v_workers: jax.Array,
    k: int,
    *,
    mask: jax.Array | None = None,
    iters: int = 16,
    key: jax.Array | None = None,
    collectives: str = "xla",
    v0: jax.Array | None = None,
    oversample: int | None = None,
    tol: float | None = None,
    wire_dtype: str = "fp32",
):
    """The distributed MERGE solve, inside ``shard_map`` over the
    ``(workers, features)`` mesh: exact-operator top-k of the masked
    mean worker projector, solved iteratively from its factors.

    ``v_workers (m_local, d_local, k)`` as in
    ``merged_lowrank_sharded`` — and this is its crossover twin: the
    factors are gathered over ``workers`` (the stack payload, same as
    the exact route), but the ``(m*k)^2`` replicated Gram eigh is
    replaced by subspace iteration on ``C C^T`` whose psums carry
    ``(m*k) x k`` — nothing quadratic in ``m*k``, nothing d-wide, no
    dense route at any shape. Above ``cfg.eigh_crossover_d`` this is
    the merge the feature-sharded trainers run. An all-masked round
    returns exact zeros (the exact route's guard semantics). ``v0``
    row shard warm-starts the iteration (the previous merged basis —
    the same lever the worker solves use).

    ``wire_dtype`` ships the worker factor-stack gather — the solve's
    one d-wide payload — in {fp32, bf16, int8} through the
    ``parallel/wire.py`` codecs (ISSUE 20). One-shot lossy (no carry
    to delta-code against): the iteration's psums, the mask gather and
    every k-wide collective stay fp32. xla collectives only."""
    psum_c, gather_c = _collective_ops(collectives)
    if wire_dtype != "fp32":
        if collectives != "xla":
            raise ValueError(
                "wire_dtype compression needs collectives='xla' (the "
                "ring route has no codec path)"
            )
        from distributed_eigenspaces_tpu.parallel.wire import (
            wire_all_gather,
        )

        c = wire_all_gather(
            v_workers, WORKER_AXIS, wire_dtype, tiled=True
        )
    else:
        c = gather_c(v_workers, WORKER_AXIS)  # (m_total, d_local, kf)
    m_total = c.shape[0]
    d_local = c.shape[1]
    if mask is None:
        w = jnp.ones((m_total,), jnp.float32)
    else:
        w = gather_c(mask, WORKER_AXIS).astype(jnp.float32)
    alive = jnp.sum(w) > 0
    cc = _scaled_factor_concat(c, w)
    if oversample is None:
        oversample = _default_oversample(k, cc.shape[1])
    mv = factor_matvec(cc, FEATURE_AXIS, alive=alive)
    v = dist_subspace_eig(
        mv, d_local, k, iters=iters, key=key,
        axis_name=FEATURE_AXIS, v0=v0, oversample=oversample, tol=tol,
    )
    return v * alive.astype(v.dtype)


def merged_top_k_distributed(
    v_stack: jax.Array,
    k: int,
    *,
    mask: jax.Array | None = None,
    iters: int = 16,
    key: jax.Array | None = None,
    v0: jax.Array | None = None,
    oversample: int | None = None,
    tol: float | None = None,
):
    """Unsharded / root-tier variant of the distributed merge solve:
    top-k of the (masked) mean of projectors from a full ``(m, d, k)``
    factor stack, by subspace iteration on ``C C^T`` — the crossover
    alternative to ``merged_top_k_lowrank`` for the flat dense
    trainers and the ROOT tier of the tiered tree merge (lower tiers
    keep the exact per-group merge: their group problems are small by
    construction). Never materializes the d x d mean projector (the
    exact route's dense dispatch when ``m*k >= d``) and never the
    ``(m*k)^2`` factor Gram."""
    m = v_stack.shape[0]
    if mask is None:
        w = jnp.ones((m,), jnp.float32)
    else:
        w = mask.astype(jnp.float32)
    alive = jnp.sum(w) > 0
    cc = _scaled_factor_concat(v_stack, w)
    if oversample is None:
        oversample = _default_oversample(k, cc.shape[1])
    mv = factor_matvec(cc, None, alive=alive)
    v = dist_subspace_eig(
        mv, v_stack.shape[1], k, iters=iters, key=key,
        axis_name=None, v0=v0, oversample=oversample, tol=tol,
    )
    return v * alive.astype(v.dtype)


def dist_extract_top_k(
    u: jax.Array,
    s: jax.Array,
    k: int,
    *,
    iters: int = 16,
    key: jax.Array | None = None,
    axis_name: str | None = FEATURE_AXIS,
    oversample: int | None = None,
):
    """The SERVING extract above the crossover: top-k eigenbasis of the
    running state ``U diag(s) U^T`` from its row-sharded factors ``u
    (d_local, r)`` / replicated ``s (r,)`` — descending order,
    globally canonical signs, returned as a ``(d_local, k)`` row shard
    (the published ``BasisVersion`` stays sharded; nothing replicates
    a d-wide buffer). Warm-started from ``u[:, :k]`` (the state's own
    leading columns — one short polish pass, not a cold solve)."""
    if oversample is None:
        oversample = _default_oversample(k, u.shape[1])
    return dist_subspace_eig(
        lowrank_matvec(u, s, axis_name),
        u.shape[0],
        k,
        iters=iters,
        key=key,
        axis_name=axis_name,
        v0=u[:, :k],
        oversample=oversample,
    )
