"""Distributed eigensolve subsystem — the d-ceiling breaker (ISSUE 15).

Every function here computes a top-k eigenbasis from *matvec access
only*, with the feature dimension optionally row-sharded over the
``features`` mesh axis: blocked randomized subspace iteration,
orthonormalized by the in-tree CholeskyQR2 row-sharded pass
(``parallel/feature_sharded.chol_qr2``), finished by a small replicated
Rayleigh–Ritz solve. No d x d buffer and no above-floor replicated
d x k ever exists on one device — enforced statically by the
``dist_solve`` contract (``analysis/contracts.py``).

Dispatch policy lives in ``PCAConfig``: ``solver="distributed"`` routes
the merge solve and the serving extract through this package whenever
``dim > cfg.eigh_crossover_d``, and keeps the exact ``eigh``-family
paths below it (equivalence angle-gated in tests and
``bench.py --dsolve``).
"""

from distributed_eigenspaces_tpu.solvers.deflation import (
    deflation_eig,
    dist_deflation_eig,
    dist_merged_top_k_deflation,
    grow_basis,
    grow_directions,
    merged_top_k_deflation,
)
from distributed_eigenspaces_tpu.solvers.distributed import (
    dist_canonicalize_signs,
    dist_extract_top_k,
    dist_merged_top_k,
    dist_rayleigh_ritz,
    dist_subspace_eig,
    factor_matvec,
    lowrank_matvec,
    merged_top_k_distributed,
    subspace_residual,
)

__all__ = [
    "deflation_eig",
    "dist_canonicalize_signs",
    "dist_deflation_eig",
    "dist_extract_top_k",
    "dist_merged_top_k",
    "dist_merged_top_k_deflation",
    "dist_rayleigh_ritz",
    "dist_subspace_eig",
    "factor_matvec",
    "grow_basis",
    "grow_directions",
    "lowrank_matvec",
    "merged_top_k_deflation",
    "merged_top_k_distributed",
    "subspace_residual",
]
